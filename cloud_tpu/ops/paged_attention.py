"""Paged decode attention as a Pallas TPU kernel.

The serving tick's hot op: one (or `spec_k + 1`) query positions per
slot attending over that slot's logical KV cache, which lives scattered
across a physical page pool (`key_pages`/`value_pages`
`[num_pages, page_size, H, D]`, serving/kvpool.py) and is addressed
through a per-slot page table. The lax path materializes a dense
`[slots, cache_len, H, D]` view by gathering the pool through the page
table every tick; this kernel never does — the page table rides as a
scalar-prefetch operand, so each grid step's K/V block is *indexed*
straight out of the pool in HBM (the gather becomes block addressing)
and streamed through VMEM with FlashAttention-style online softmax.

Grid and masking contract (see /opt/skills/guides/pallas_guide.md):
- Grid is (slots*heads, pages_per_slot) with the page dimension
  innermost. Program (b, j) serves slot b // H, head b % H, and logical
  page j; its K/V block is physical page `page_table[b // H, j]` —
  `PrefetchScalarGridSpec` places the table in SMEM before the kernel
  runs so the BlockSpec index maps can read it.
- VMEM scratch (acc, m, l) carries the online-softmax state across page
  steps; the output block is written on the last page step. m/l live in
  (seq_pad, 128) lane-broadcast scratch (Mosaic has no cheap
  (N,1)<->(1,N) transpose).
- Masking is purely the caller's `allowed [slots, seq, cache_len]`
  (from `decoding.paged_slot_update`): it already encodes per-query
  causality over *logical* key slots plus slot validity, so freed /
  never-written / scratch-page-0 entries carry exact-zero weight — the
  kernel zeroes masked probabilities explicitly (`p = where(mask, ...)`)
  rather than relying on exp underflow, so a fully-masked row (e.g. a
  padded query row or an evicted slot) outputs zeros, never a uniform
  average over pool garbage.
- `seq` (1 for the plain tick, spec_k + 1 for the speculative verify
  window) is padded to a sublane multiple; padded query rows are
  all-masked and sliced away.

The gathered-lax reference below is bitwise the math
`models/transformer.py::_paged_decode_attention` shipped before this
kernel (gather -> f32 einsum -> -1e30 mask -> softmax -> cast ->
einsum), so engine-vs-solo bit-identity pins keep holding wherever the
reference is selected. Off-TPU the kernel path executes as
`_paged_walk_lax` — the same page-block walk and online-softmax update
order, vectorized in lax (Mosaic can't compile there, and Pallas
interpret mode is two orders of magnitude too slow for a serving
tick) — which is what the `CLOUD_TPU_PAGED_KERNEL=1` smoke measures;
the parity suite additionally forces `interpret=True` to pin the true
interpreted kernel against both the walk and the reference.

Quantized pages (graftpack): with `key_scales`/`value_scales` given
(`[num_pages, heads]` f32, per-page per-head symmetric scales), the
K/V pages are int8 and every impl dequantizes INSIDE its block load —
the dequant contract, identical across kernel/walk/reference:

    k_f32 = k_int8.astype(f32) * scale[page, head]

and both the QK and PV dots run in f32 (int8 quantization already
costs ~0.4% relative error, so bf16 intermediate rounding would
dominate it). In the kernel the scale is ONE SMEM scalar per grid
step — it rides scalar prefetch next to the page table, the dequant
folds into the dots as a scalar multiply, and nothing dequantized is
ever materialized in HBM. The walk and reference grow the same math,
so the parity suite covers all three impls in int8 mode too. A zero
scale means an all-zero (never-written) page and dequantizes to exact
zeros.

Forward only: decode never differentiates through the cache.
"""

import functools
import math
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128
_SUBLANES = 8


class _PagedConfig(NamedTuple):
    sm_scale: float
    heads: int
    seq_pad: int     # query rows after sublane padding
    page_size: int
    interpret: bool
    quantized: bool = False


def _check_scales(key_pages, key_scales, value_scales):
    """Validates the int8-page calling convention: both scale arrays or
    neither; int8 pages; [num_pages, heads] f32 scales."""
    if (key_scales is None) != (value_scales is None):
        raise ValueError(
            "key_scales and value_scales must be given together.")
    if key_scales is None:
        return False
    num_pages, _, heads, _ = key_pages.shape
    if key_pages.dtype != jnp.int8:
        raise ValueError(
            "scales imply int8 pages; got page dtype {}.".format(
                key_pages.dtype))
    for name, s in (("key_scales", key_scales),
                    ("value_scales", value_scales)):
        if s.shape != (num_pages, heads):
            raise ValueError(
                "{} must be [num_pages, heads] = {}; got {}.".format(
                    name, (num_pages, heads), s.shape))
    return True


def paged_attention_reference(q, key_pages, value_pages, page_table,
                              allowed, sm_scale=None, key_scales=None,
                              value_scales=None):
    """Gathered-lax paged decode attention (the correctness oracle).

    q: [slots, seq, H, D]; key_pages/value_pages: [N, P, H, D];
    page_table: [slots, pages_per_slot] int32; allowed:
    [slots, seq, cache_len] bool (True = attend) ->
    [slots, seq, H, D] in the page dtype (q's dtype for int8 pages).

    Logical per-slot [cache_len] views, one gather per call — bitwise
    the pre-kernel serving-tick math, kept verbatim so the kernel-off
    engine stays bit-identical to solo `generate()` decodes. With
    `key_scales`/`value_scales` the int8 pages are dequantized into
    the gathered f32 view (the module-level dequant contract) and the
    whole computation stays f32.
    """
    num_pages, page_size, heads, head_dim = key_pages.shape
    slots, pages_per_slot = page_table.shape
    cache_len = pages_per_slot * page_size
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(head_dim)
    quantized = _check_scales(key_pages, key_scales, value_scales)
    if quantized:
        ks = key_scales[page_table][:, :, None, :, None]
        vs = value_scales[page_table][:, :, None, :, None]
        k_view = (key_pages[page_table].astype(jnp.float32) * ks
                  ).reshape(slots, cache_len, heads, head_dim)
        v_view = (value_pages[page_table].astype(jnp.float32) * vs
                  ).reshape(slots, cache_len, heads, head_dim)
    else:
        k_view = key_pages[page_table].reshape(slots, cache_len, heads,
                                               head_dim)
        v_view = value_pages[page_table].reshape(slots, cache_len,
                                                 heads, head_dim)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_view,
                        preferred_element_type=jnp.float32) * sm_scale
    logits = jnp.where(allowed[:, None], logits, _NEG_INF)
    out_dtype = q.dtype if quantized else value_pages.dtype
    weights = jax.nn.softmax(logits, axis=-1).astype(
        jnp.float32 if quantized else value_pages.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights,
                      v_view).astype(out_dtype)


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------


def _paged_kernel(pt_ref, q_ref, k_ref, v_ref, a_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, config, num_pages):
    del pt_ref  # consumed by the BlockSpec index maps
    ji = pl.program_id(1)

    @pl.when(ji == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0]             # [seq_pad, D]
    k = k_ref[0, :, 0, :]    # [P, D] — physical page pt[slot, ji]
    v = v_ref[0, :, 0, :]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * config.sm_scale
    mask = a_ref[0, :, 0, :] != 0

    s = jnp.where(mask, s, _NEG_INF)
    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_curr = jnp.max(s, axis=-1, keepdims=True)
    m_next = jnp.maximum(m_prev, m_curr)
    alpha = jnp.exp(m_prev - m_next)
    # Explicit zero where masked: exp(s - m) underflows to 0 for normal
    # rows, but a fully-masked row (padded query, evicted slot, scratch
    # page) has m == s == -inf and exp(0) == 1 would leak pool garbage.
    p = jnp.where(mask, jnp.exp(s - m_next), 0.0)
    l_next = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[...] = jnp.broadcast_to(m_next, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_next, l_ref.shape)

    @pl.when(ji == num_pages - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)


def _paged_kernel_quant(pt_ref, ks_ref, vs_ref, q_ref, k_ref, v_ref,
                        a_ref, o_ref, acc_ref, m_ref, l_ref, *,
                        config, num_pages):
    """Int8-page variant: same online softmax, with each block's
    per-page per-head f32 scale read as ONE SMEM scalar (it rides
    scalar prefetch next to the page table) and folded into the dots —
    `s = dot(q, k_i8) * (ks * sm_scale)` and `acc += dot(p, v_i8) * vs`
    are exactly the pre-dot dequant contract because the scale is
    constant over the block. Both dots run in f32 (module docstring)."""
    b = pl.program_id(0)
    ji = pl.program_id(1)
    page = pt_ref[b // config.heads, ji]
    ks = ks_ref[page, b % config.heads]
    vs = vs_ref[page, b % config.heads]

    @pl.when(ji == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32)          # [seq_pad, D]
    k = k_ref[0, :, 0, :].astype(jnp.float32)  # [P, D] int8 -> f32
    v = v_ref[0, :, 0, :].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * (ks * config.sm_scale)
    mask = a_ref[0, :, 0, :] != 0

    s = jnp.where(mask, s, _NEG_INF)
    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_curr = jnp.max(s, axis=-1, keepdims=True)
    m_next = jnp.maximum(m_prev, m_curr)
    alpha = jnp.exp(m_prev - m_next)
    p = jnp.where(mask, jnp.exp(s - m_next), 0.0)
    l_next = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * vs
    m_ref[...] = jnp.broadcast_to(m_next, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_next, l_ref.shape)

    @pl.when(ji == num_pages - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)


def _paged_forward(config, q, key_pages, value_pages, page_table,
                   allowed, key_scales=None, value_scales=None):
    """q: [S*H, seq_pad, D] (head-folded); allowed:
    [S, seq_pad, pages_per_slot, P] int32 -> out [S*H, seq_pad, D].

    The page table is the scalar-prefetch operand: index maps read
    `pt[b // H, j]` to address each program's physical K/V page, so the
    pool is only ever touched at the pages a slot actually owns. In
    int8 mode the scale arrays join it in SMEM (num_scalar_prefetch=3)
    and the kernel reads one scalar per grid step.
    """
    bh, seq_pad, head_dim = q.shape
    heads = config.heads
    page_size = config.page_size
    pages_per_slot = page_table.shape[1]
    grid = (bh, pages_per_slot)
    n_scalar = 3 if config.quantized else 1
    kern = _paged_kernel_quant if config.quantized else _paged_kernel
    kernel = functools.partial(kern, config=config,
                               num_pages=pages_per_slot)

    def _drop(index_map):
        # Index maps receive every scalar-prefetch operand; only the
        # page table is ever indexed.
        return lambda b, j, pt, *_: index_map(b, j, pt)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_scalar,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, seq_pad, head_dim),
                         _drop(lambda b, j, pt: (b, 0, 0))),
            # K/V blocks are single physical pages, gathered by block
            # *indexing* through the prefetched table — never an HBM
            # materialization of the dense [S, cache_len, H, D] view.
            pl.BlockSpec((1, page_size, 1, head_dim),
                         _drop(lambda b, j, pt: (pt[b // heads, j], 0,
                                                 b % heads, 0))),
            pl.BlockSpec((1, page_size, 1, head_dim),
                         _drop(lambda b, j, pt: (pt[b // heads, j], 0,
                                                 b % heads, 0))),
            # The singleton page axis keeps the mask block's last dim
            # equal to the array dim (Mosaic's lane rule for P < 128).
            pl.BlockSpec((1, seq_pad, 1, page_size),
                         _drop(lambda b, j, pt: (b // heads, 0, j, 0))),
        ],
        out_specs=pl.BlockSpec((1, seq_pad, head_dim),
                               _drop(lambda b, j, pt: (b, 0, 0))),
        scratch_shapes=[
            pltpu.VMEM((seq_pad, head_dim), jnp.float32),
            pltpu.VMEM((seq_pad, _LANES), jnp.float32),
            pltpu.VMEM((seq_pad, _LANES), jnp.float32),
        ],
    )
    out_dtype = q.dtype if config.quantized else value_pages.dtype
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bh, seq_pad, head_dim),
                                       out_dtype),
        interpret=config.interpret,
    )
    if config.quantized:
        return call(page_table, key_scales, value_scales, q,
                    key_pages, value_pages, allowed)
    return call(page_table, q, key_pages, value_pages, allowed)


def _paged_walk_lax(q, key_pages, value_pages, page_table, allowed,
                    sm_scale, key_scales=None, value_scales=None):
    """The kernel's defining math as vectorized lax: walk the page
    blocks in grid order, gathering ONLY the slots' own pages (one
    [slots, P, H, D] take per logical page — never the dense
    [slots, cache_len] view), with the exact online-softmax update
    sequence `_paged_kernel` runs per step. This is the off-TPU
    execution of the kernel path: Mosaic can't compile there and
    Pallas interpret mode is ~100x too slow for a serving tick, so the
    `CLOUD_TPU_PAGED_KERNEL=1` smoke runs this form while the parity
    suite pins it against the true interpreted kernel
    (`interpret=True`) and the gathered reference. Int8 pages are
    dequantized per page block in f32 (the module dequant contract)."""
    num_pages, page_size, heads, head_dim = key_pages.shape
    slots, seq, q_heads, _ = q.shape
    pages_per_slot = page_table.shape[1]
    quantized = key_scales is not None
    am = allowed.reshape(slots, seq, pages_per_slot, page_size)
    m = jnp.full((slots, heads, seq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((slots, heads, seq, 1), jnp.float32)
    acc = jnp.zeros((slots, heads, seq, head_dim), jnp.float32)
    for j in range(pages_per_slot):
        pages = page_table[:, j]
        k = key_pages[pages]                 # [slots, P, H, D]
        v = value_pages[pages]
        if quantized:
            k = k.astype(jnp.float32) * key_scales[pages][:, None, :,
                                                          None]
            v = v.astype(jnp.float32) * value_scales[pages][:, None, :,
                                                            None]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * sm_scale
        mask = am[:, :, j, :][:, None]       # [slots, 1, seq, P]
        s = jnp.where(mask, s, _NEG_INF)
        m_curr = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m, m_curr)
        alpha = jnp.exp(m - m_next)
        p = jnp.where(mask, jnp.exp(s - m_next), 0.0)
        l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bkhd->bhqd",
                                       p.astype(v.dtype), v)
        m = m_next
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out_dtype = q.dtype if quantized else value_pages.dtype
    out = (acc / safe_l).astype(out_dtype)
    return jnp.transpose(out, (0, 2, 1, 3))


def paged_decode_attention(q, key_pages, value_pages, page_table,
                           allowed, sm_scale=None,
                           interpret: Optional[bool] = None,
                           key_scales=None, value_scales=None):
    """Pallas paged decode attention; layouts as the reference.

    Handles both the seq=1 plain tick and the seq=spec_k+1 speculative
    verify window (query rows are sublane-padded; padded rows are
    all-masked and sliced away). Output matches
    `paged_attention_reference` to online-softmax accumulation order —
    tolerance-level, not bitwise; fully-masked rows (evicted slots,
    padded queries) output exact zeros. With scales given the pages
    are int8 and the kernel dequantizes in its block loads (module
    docstring).

    interpret: None (default) compiles the kernel on TPU and runs the
    lax page-walk form of the same math elsewhere; True forces Pallas
    interpret mode (the parity suite's same-code-path check — far too
    slow for a serving tick).
    """
    num_pages, page_size, heads, head_dim = key_pages.shape
    slots, seq, q_heads, _ = q.shape
    pages_per_slot = page_table.shape[1]
    cache_len = pages_per_slot * page_size
    if q_heads != heads:
        raise ValueError(
            "q heads ({}) must match page heads ({}) — the paged "
            "decode cache stores full-width heads.".format(q_heads,
                                                           heads))
    if value_pages.shape != key_pages.shape:
        raise ValueError(
            "key_pages and value_pages must have identical shapes; "
            "got {} vs {}.".format(key_pages.shape, value_pages.shape))
    if allowed.shape != (slots, seq, cache_len):
        raise ValueError(
            "allowed must be [slots, seq, cache_len] = {}; got "
            "{}.".format((slots, seq, cache_len), allowed.shape))
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(head_dim)
    quantized = _check_scales(key_pages, key_scales, value_scales)
    if interpret is None:
        if jax.default_backend() != "tpu":
            return _paged_walk_lax(q, key_pages, value_pages,
                                   page_table, allowed,
                                   float(sm_scale),
                                   key_scales=key_scales,
                                   value_scales=value_scales)
        interpret = False

    seq_pad = -(-seq // _SUBLANES) * _SUBLANES
    config = _PagedConfig(sm_scale=float(sm_scale), heads=heads,
                          seq_pad=seq_pad, page_size=page_size,
                          interpret=bool(interpret),
                          quantized=quantized)

    qf = jnp.transpose(q, (0, 2, 1, 3)).reshape(slots * heads, seq,
                                                head_dim)
    amask = allowed.astype(jnp.int32)
    if seq_pad != seq:
        qf = jnp.pad(qf, ((0, 0), (0, seq_pad - seq), (0, 0)))
        # Padded query rows are fully masked -> zero output rows.
        amask = jnp.pad(amask, ((0, 0), (0, seq_pad - seq), (0, 0)))
    amask = amask.reshape(slots, seq_pad, pages_per_slot, page_size)

    out = _paged_forward(config, qf, key_pages, value_pages,
                         page_table.astype(jnp.int32), amask,
                         key_scales=key_scales,
                         value_scales=value_scales)
    out = out[:, :seq].reshape(slots, heads, seq, head_dim)
    return jnp.transpose(out, (0, 2, 1, 3))


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def paged_attention(q, key_pages, value_pages, page_table, allowed,
                    sm_scale=None, impl="auto",
                    interpret: Optional[bool] = None,
                    key_scales=None, value_scales=None):
    """Dispatching paged decode attention: Pallas kernel or gathered lax.

    impl: "paged" forces the kernel, "reference" forces the gathered
    lax path; "auto" (and any training-side impl name such as "flash",
    which has no paged analogue) picks the kernel on TPU and the
    reference elsewhere. The `CLOUD_TPU_PAGED_KERNEL` env var is the
    deployment/A-B override and beats `impl`: "1" forces the kernel
    (interpret mode off-TPU, so CPU CI drives the kernel code path),
    "0" forces the reference, unset/empty defers to `impl`.
    key_scales/value_scales select int8-page mode on whichever impl is
    picked (the dequant contract in the module docstring).
    """
    env = os.environ.get("CLOUD_TPU_PAGED_KERNEL", "").strip()
    if env == "1":
        use_kernel = True
    elif env == "0":
        use_kernel = False
    elif impl == "paged":
        use_kernel = True
    elif impl == "reference":
        use_kernel = False
    else:
        use_kernel = jax.default_backend() == "tpu"
    if use_kernel:
        return paged_decode_attention(q, key_pages, value_pages,
                                      page_table, allowed,
                                      sm_scale=sm_scale,
                                      interpret=interpret,
                                      key_scales=key_scales,
                                      value_scales=value_scales)
    return paged_attention_reference(q, key_pages, value_pages,
                                     page_table, allowed,
                                     sm_scale=sm_scale,
                                     key_scales=key_scales,
                                     value_scales=value_scales)


def paged_attention_cost(slots, seq, heads, head_dim, page_size,
                         pages_per_slot, dtype=jnp.bfloat16,
                         kv_dtype=None):
    """Per-call flops / bytes-moved row for the telemetry gauges.

    flops come from the jit cost-analysis hook (the PR 6 idiom —
    `lower().cost_analysis()`, list-unwrapped, exception-swallowed) on
    the gathered reference at these shapes; bytes_moved is the kernel's
    HBM traffic (q + out + the slot's own K/V pages + table + mask),
    i.e. what the fused path touches — NOT the dense gather the
    reference materializes. kv_dtype (default: `dtype`) sizes the K/V
    page traffic separately so int8 pages report their real, smaller
    byte movement (plus the per-page f32 scale reads). Returns
    {"flops", "bytes_moved"}; never raises (falls back to the analytic
    flop count).
    """
    cache_len = page_size * pages_per_slot
    num_pages = slots * pages_per_slot + 1
    itemsize = jnp.dtype(dtype).itemsize
    kv_itemsize = jnp.dtype(kv_dtype or dtype).itemsize
    quantized = kv_dtype is not None and jnp.dtype(kv_dtype) == jnp.int8
    # 2 matmuls (qk^T, pv), 2 flops per MAC.
    flops = 4.0 * slots * seq * cache_len * heads * head_dim
    try:
        shapes = (
            jax.ShapeDtypeStruct((slots, seq, heads, head_dim), dtype),
            jax.ShapeDtypeStruct((num_pages, page_size, heads,
                                  head_dim), dtype),
            jax.ShapeDtypeStruct((num_pages, page_size, heads,
                                  head_dim), dtype),
            jax.ShapeDtypeStruct((slots, pages_per_slot), jnp.int32),
            jax.ShapeDtypeStruct((slots, seq, cache_len), jnp.bool_),
        )
        analysis = jax.jit(paged_attention_reference).lower(
            *shapes).cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        flops = float(analysis.get("flops", flops) or flops)
    except Exception:
        pass
    bytes_moved = float(
        2 * slots * cache_len * heads * head_dim * kv_itemsize  # K/V
        + 2 * slots * seq * heads * head_dim * itemsize       # q + out
        + slots * pages_per_slot * 4                          # table
        + slots * seq * cache_len)                            # mask
    if quantized:
        # Per-page per-head f32 K and V scales ride scalar prefetch.
        bytes_moved += float(2 * slots * pages_per_slot * heads * 4)
    return {"flops": flops, "bytes_moved": bytes_moved}
