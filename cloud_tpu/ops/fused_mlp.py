"""Fused SwiGLU MLP tail as a Pallas TPU kernel.

The gated MLP `down(act(gate(x)) * up(x))` is the last unfused hot op
in the Llama block: written as three `nn.Dense` calls it materializes
the two `[rows, d_ff]` projections and the gated product in HBM between
matmuls. This kernel streams a row block through VMEM once — both input
projections, the gate nonlinearity, the elementwise product, and the
down projection happen per block with the three weight matrices held
resident — so the `[rows, d_ff]` intermediates never touch HBM.

Numerics mirror the flax module exactly: inputs and kernels are cast to
the compute dtype (flax `promote_dtype` with `dtype=compute_dtype`),
each projection is a plain `lax.dot_general` with default precision,
and the activation runs on the projected compute-dtype values — so
swapping the unfused SwiGLU for this op is bitwise in f32 and
tolerance-level in bf16 (same rounding points, blocked rows don't
change a row's reduction).

Backward is `jax.custom_vjp` with the standard gated-MLP gradient in
f32 from the saved (x, weights): dh = dy@Wd^T, du = dh*act(g),
da = dh*u, dg via the activation's own vjp, dx = dg@Wg^T + du@Wu^T,
and the three kernel grads from the corresponding outer products. The
backward runs as plain lax — decode never differentiates, and the
single-pass claim is for the forward serving/training hot path.

On non-TPU backends a forced kernel runs in Pallas interpret mode, so
parity tests exercise the same code path CPU-side.
"""

import functools
import os
import types
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_BLOCK_ROWS = 128

# Mirrors llama._GATE_ACTIVATIONS (ops must not import models); flax
# nn.silu/nn.gelu ARE jax.nn.silu/jax.nn.gelu, so the reference stays
# math-for-math the module. Immutable: traced functions bake the
# lookup in at trace time, so the table must never change underneath
# a warm executable.
_ACTIVATIONS = types.MappingProxyType({
    "silu": jax.nn.silu,
    "gelu_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
})


class _MLPConfig(NamedTuple):
    activation: str
    block_rows: int
    out_dtype: str   # dtype name (hashable for the custom_vjp config)
    interpret: bool


def _contract(x, w):
    """The exact `nn.Dense(use_bias=False)` contraction: last axis of x
    against axis 0 of w, default precision."""
    return jax.lax.dot_general(
        x, w, (((x.ndim - 1,), (0,)), ((), ())))


def swiglu_reference(x, w_gate, w_up, w_down, activation="silu",
                     compute_dtype=None):
    """Pure-lax gated MLP: down(act(gate(x)) * up(x)).

    Math-for-math the flax SwiGLU module (three bias-free `nn.Dense`
    with `dtype=compute_dtype`): everything is cast to `compute_dtype`
    up front (flax `promote_dtype` semantics; the promoted type of
    x/w_gate when None), then three default-precision dot_generals with
    the activation on the projected values.
    """
    try:
        act = _ACTIVATIONS[activation]
    except KeyError:
        raise ValueError(
            "Unknown mlp activation {!r}; expected one of {}.".format(
                activation, sorted(_ACTIVATIONS)))
    if compute_dtype is None:
        compute_dtype = jnp.promote_types(x.dtype, w_gate.dtype)
    x = x.astype(compute_dtype)
    g = _contract(x, w_gate.astype(compute_dtype))
    u = _contract(x, w_up.astype(compute_dtype))
    return _contract(act(g) * u, w_down.astype(compute_dtype))


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(x_ref, wg_ref, wu_ref, wd_ref, o_ref, *, config):
    """One row block: both projections, the gated product, and the down
    projection — one VMEM pass, weights resident across the grid."""
    act = _ACTIVATIONS[config.activation]
    x = x_ref[...]
    g = jnp.dot(x, wg_ref[...])
    u = jnp.dot(x, wu_ref[...])
    o_ref[...] = jnp.dot(act(g) * u, wd_ref[...]).astype(o_ref.dtype)


def _swiglu_forward(config, x, w_gate, w_up, w_down):
    """x: [rows, D] (row-padded, compute dtype); weights compute dtype
    -> [rows, D_out] out_dtype."""
    rows, features = x.shape
    d_ff = w_gate.shape[1]
    d_out = w_down.shape[1]
    block = config.block_rows
    grid = (rows // block,)
    kernel = functools.partial(_fwd_kernel, config=config)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, features), lambda i: (i, 0)),
            pl.BlockSpec((features, d_ff), lambda i: (0, 0)),
            pl.BlockSpec((features, d_ff), lambda i: (0, 0)),
            pl.BlockSpec((d_ff, d_out), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block, d_out), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d_out),
                                       jnp.dtype(config.out_dtype)),
        interpret=config.interpret,
    )(x, w_gate, w_up, w_down)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fused_swiglu(config, x, w_gate, w_up, w_down):
    return _swiglu_forward(config, x, w_gate, w_up, w_down)


def _fused_swiglu_fwd(config, x, w_gate, w_up, w_down):
    out = _swiglu_forward(config, x, w_gate, w_up, w_down)
    return out, (x, w_gate, w_up, w_down)


def _fused_swiglu_bwd(config, residuals, dy):
    x, w_gate, w_up, w_down = residuals
    act = _ACTIVATIONS[config.activation]
    xf = x.astype(jnp.float32)
    wgf = w_gate.astype(jnp.float32)
    wuf = w_up.astype(jnp.float32)
    wdf = w_down.astype(jnp.float32)
    g = xf @ wgf
    u = xf @ wuf
    a, act_vjp = jax.vjp(act, g)
    dyf = dy.astype(jnp.float32)
    dh = dyf @ wdf.T
    dwd = (a * u).T @ dyf
    du = dh * a
    da = dh * u
    dg = act_vjp(da)[0]
    dx = dg @ wgf.T + du @ wuf.T
    dwg = xf.T @ dg
    dwu = xf.T @ du
    return (dx.astype(x.dtype), dwg.astype(w_gate.dtype),
            dwu.astype(w_up.dtype), dwd.astype(w_down.dtype))


_fused_swiglu.defvjp(_fused_swiglu_fwd, _fused_swiglu_bwd)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def fused_swiglu(x, w_gate, w_up, w_down, activation="silu",
                 compute_dtype=None, impl="auto",
                 interpret: Optional[bool] = None, block_rows=None):
    """Dispatching fused SwiGLU tail: down(act(gate(x)) * up(x)).

    x: [..., D]; w_gate/w_up: [D, F]; w_down: [F, D_out] (the bare
    `kernel` params of the three bias-free Dense projections, any
    param dtype — cast to `compute_dtype` here, flax-style).

    impl: "fused" forces the Pallas kernel, "reference" the lax path;
    "auto" picks the kernel on TPU, the reference elsewhere. The
    `CLOUD_TPU_FUSED_MLP` env var ("1"/"0") is the deployment A/B
    override and beats `impl`; a forced kernel runs in interpret mode
    off-TPU. Differentiable w.r.t. x and all three weights either way.
    """
    features = x.shape[-1]
    if w_gate.ndim != 2 or w_gate.shape[0] != features:
        raise ValueError(
            "w_gate must be [features={}, d_ff]; got {}.".format(
                features, w_gate.shape))
    if w_up.shape != w_gate.shape:
        raise ValueError(
            "w_up must match w_gate's shape {}; got {}.".format(
                w_gate.shape, w_up.shape))
    if w_down.ndim != 2 or w_down.shape[0] != w_gate.shape[1]:
        raise ValueError(
            "w_down must be [d_ff={}, d_out]; got {}.".format(
                w_gate.shape[1], w_down.shape))
    env = os.environ.get("CLOUD_TPU_FUSED_MLP", "").strip()
    if env == "1":
        use_kernel = True
    elif env == "0":
        use_kernel = False
    elif impl == "fused":
        use_kernel = True
    elif impl == "reference":
        use_kernel = False
    else:
        use_kernel = jax.default_backend() == "tpu"
    if not use_kernel:
        return swiglu_reference(x, w_gate, w_up, w_down,
                                activation=activation,
                                compute_dtype=compute_dtype)

    if activation not in _ACTIVATIONS:
        raise ValueError(
            "Unknown mlp activation {!r}; expected one of {}.".format(
                activation, sorted(_ACTIVATIONS)))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_rows is None:
        block_rows = int(os.environ.get("CLOUD_TPU_FUSED_MLP_BLOCK",
                                        _BLOCK_ROWS))
    if compute_dtype is None:
        compute_dtype = jnp.promote_types(x.dtype, w_gate.dtype)
    lead = x.shape[:-1]
    rows = 1
    for dim in lead:
        rows *= dim
    block_rows = min(block_rows, max(rows, 1))
    rows_pad = -(-rows // block_rows) * block_rows
    config = _MLPConfig(activation=activation,
                        block_rows=int(block_rows),
                        out_dtype=jnp.dtype(compute_dtype).name,
                        interpret=bool(interpret))
    folded = x.astype(compute_dtype).reshape(rows, features)
    if rows_pad != rows:
        # Zero rows project to zero, gate to act(0)*0 = 0 — sliced
        # away below; pad/slice autodiff owns the edges.
        folded = jnp.pad(folded, ((0, rows_pad - rows), (0, 0)))
    out = _fused_swiglu(config, folded,
                        w_gate.astype(compute_dtype),
                        w_up.astype(compute_dtype),
                        w_down.astype(compute_dtype))
    return out[:rows].reshape(lead + (w_down.shape[1],))


def fused_mlp_cost(shape, d_ff, dtype=jnp.bfloat16):
    """Per-call flops / bytes-moved row for the telemetry gauges, via
    the jit cost-analysis hook on the lax reference (PR 6 idiom);
    bytes_moved is the fused single-pass traffic (x in, y out, three
    weights — the [rows, d_ff] intermediates stay in VMEM). Returns
    {"flops", "bytes_moved"}; never raises."""
    rows = 1
    for dim in shape[:-1]:
        rows *= dim
    features = shape[-1]
    flops = 6.0 * rows * features * d_ff  # three matmuls
    try:
        args = [jax.ShapeDtypeStruct(tuple(shape), dtype),
                jax.ShapeDtypeStruct((features, d_ff), jnp.float32),
                jax.ShapeDtypeStruct((features, d_ff), jnp.float32),
                jax.ShapeDtypeStruct((d_ff, features), jnp.float32)]
        analysis = jax.jit(swiglu_reference).lower(
            *args).cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        flops = float(analysis.get("flops", flops) or flops)
    except Exception:
        pass
    itemsize = jnp.dtype(dtype).itemsize
    bytes_moved = float(2 * rows * features * itemsize
                        + 3 * features * d_ff * 4)
    return {"flops": flops, "bytes_moved": bytes_moved}


def record_cost_row(shape, d_ff, dtype=jnp.bfloat16, iters=10):
    """Times the jitted fused tail at `shape` and feeds the telemetry
    kernel-cost row (`cloud_tpu_kernel_fused_mlp_pct_peak` /
    `_bytes_moved`) — the bench/CI hook that turns the cost analysis
    into a tracked pct-of-peak metric. No-op (returns None) when
    telemetry is off; returns the per-call seconds otherwise."""
    import sys
    import time

    telemetry = sys.modules.get("cloud_tpu.monitoring.telemetry")
    if telemetry is None:
        return None
    tele = telemetry.get()
    if tele is None or not tele.active:
        return None
    import numpy as np

    rng = np.random.RandomState(0)
    features = shape[-1]
    x = jnp.asarray(rng.randn(*shape), dtype)
    w_gate = jnp.asarray(rng.randn(features, d_ff) * 0.02, jnp.float32)
    w_up = jnp.asarray(rng.randn(features, d_ff) * 0.02, jnp.float32)
    w_down = jnp.asarray(rng.randn(d_ff, features) * 0.02, jnp.float32)

    @jax.jit
    def run(x, w_gate, w_up, w_down):
        return fused_swiglu(x, w_gate, w_up, w_down)

    jax.block_until_ready(run(x, w_gate, w_up, w_down))  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run(x, w_gate, w_up, w_down)
    jax.block_until_ready(out)
    elapsed = (time.perf_counter() - t0) / max(iters, 1)
    cost = fused_mlp_cost(shape, d_ff, dtype)
    tele.record_kernel_cost("fused_mlp", cost["flops"],
                            cost["bytes_moved"], elapsed)
    return elapsed
