"""Fused RMSNorm + residual-add as a Pallas TPU kernel.

The pre-norm transformer tail `h = x + residual; y = rmsnorm(h) * scale`
is two HBM round trips when written as separate ops (the residual add
materializes h, the norm re-reads it). This kernel does both in ONE HBM
pass: each grid step streams a row block through VMEM, adds the
residual, computes the f32 row statistics, and writes BOTH the normed
rows and the updated residual stream h.

Numerics mirror `flax.linen.RMSNorm` exactly: statistics are computed
in f32 on the promoted input (`var = mean(h_f32^2)`), the scale param is
f32 `[features]`, and the output is `h * (rsqrt(var + eps) * scale)`
cast to the requested dtype — so swapping a flax norm for this op is a
bitwise no-op in f32 and tolerance-level in bf16 (same single rounding
point).

Backward is `jax.custom_vjp` with the standard RMSNorm gradient
recomputed from the saved h (one residual tensor, no (x, residual)
pair): dh folds the normed-output cotangent AND the residual-stream
cotangent, and both inputs of the fused add receive it. The backward
runs as plain lax — decode never differentiates, and training backward
is dominated by the matmuls either way; the single-pass claim is for
the forward serving/training hot path.

On non-TPU backends a forced kernel runs in Pallas interpret mode, so
parity tests exercise the same code path CPU-side.
"""

import functools
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_BLOCK_ROWS = 128


class _NormConfig(NamedTuple):
    eps: float
    block_rows: int
    out_dtype: str   # dtype name (hashable for the custom_vjp config)
    interpret: bool


def rmsnorm_residual_reference(x, scale, residual=None, eps=1e-6,
                               out_dtype=None):
    """Pure-lax fused norm tail: returns (normed, h).

    h = x + residual (or x when residual is None); normed is flax
    `RMSNorm(epsilon=eps, dtype=out_dtype)` applied to h, math-for-math
    (f32 statistics on the promoted input, `h * (rsqrt(var+eps)*scale)`,
    one cast at the end).
    """
    h = x if residual is None else x + residual
    if out_dtype is None:
        out_dtype = h.dtype
    hf = h.astype(jnp.float32)
    var = jnp.mean(hf * hf, axis=-1, keepdims=True)
    mul = jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    return (hf * mul).astype(out_dtype), h


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(x_ref, r_ref, w_ref, o_ref, h_ref, *, config):
    """One row block: h = x (+ r), f32 stats, normed — one VMEM pass."""
    if r_ref is None:
        h = x_ref[...]
    else:
        h = x_ref[...] + r_ref[...]
        h_ref[...] = h
    hf = h.astype(jnp.float32)
    var = jnp.mean(hf * hf, axis=-1, keepdims=True)
    mul = jax.lax.rsqrt(var + config.eps) * w_ref[...]
    o_ref[...] = (hf * mul).astype(o_ref.dtype)


def _norm_forward(config, x, residual, scale):
    """x/residual: [rows, D] (row-padded); scale: [1, D] f32 ->
    (normed [rows, D] out_dtype, h [rows, D] x.dtype)."""
    rows, features = x.shape
    block = config.block_rows
    grid = (rows // block,)
    out_dtype = jnp.dtype(config.out_dtype)
    row_spec = pl.BlockSpec((block, features), lambda i: (i, 0))
    w_spec = pl.BlockSpec((1, features), lambda i: (0, 0))
    if residual is None:
        kernel = functools.partial(
            lambda x_ref, w_ref, o_ref, **kw: _fwd_kernel(
                x_ref, None, w_ref, o_ref, None, **kw),
            config=config)
        normed = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[row_spec, w_spec],
            out_specs=row_spec,
            out_shape=jax.ShapeDtypeStruct((rows, features), out_dtype),
            interpret=config.interpret,
        )(x, scale)
        return normed, x
    kernel = functools.partial(_fwd_kernel, config=config)
    normed, h = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[row_spec, row_spec, w_spec],
        out_specs=[row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, features), out_dtype),
            jax.ShapeDtypeStruct((rows, features), x.dtype),
        ],
        interpret=config.interpret,
    )(x, residual, scale)
    return normed, h


def _norm_bwd_math(config, h, scale, g_normed, g_h):
    """Standard RMSNorm gradient in f32 from the saved residual stream:
    dh = g*w*r - h * r^3/D * sum(g*w*h) (+ the h cotangent), with both
    fused-add inputs receiving dh; dscale sums over rows."""
    features = h.shape[-1]
    hf = h.astype(jnp.float32)
    gf = g_normed.astype(jnp.float32)
    w = scale.astype(jnp.float32)
    var = jnp.mean(hf * hf, axis=-1, keepdims=True)
    r = jax.lax.rsqrt(var + config.eps)
    gw = gf * w
    inner = jnp.sum(gw * hf, axis=-1, keepdims=True)
    dh = gw * r - hf * (r * r * r / features) * inner
    if g_h is not None:
        dh = dh + g_h.astype(jnp.float32)
    dscale = jnp.sum(gf * hf * r, axis=0,
                     keepdims=True).astype(scale.dtype)
    return dh, dscale


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fused_rmsnorm(config, x, scale):
    return _norm_forward(config, x, None, scale)


def _fused_rmsnorm_fwd(config, x, scale):
    out = _norm_forward(config, x, None, scale)
    return out, (x, scale)


def _fused_rmsnorm_bwd(config, residuals, grads):
    x, scale = residuals
    g_normed, g_h = grads
    dh, dscale = _norm_bwd_math(config, x, scale, g_normed, g_h)
    return dh.astype(x.dtype), dscale


_fused_rmsnorm.defvjp(_fused_rmsnorm_fwd, _fused_rmsnorm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fused_rmsnorm_residual(config, x, residual, scale):
    return _norm_forward(config, x, residual, scale)


def _fused_rmsnorm_residual_fwd(config, x, residual, scale):
    normed, h = _norm_forward(config, x, residual, scale)
    return (normed, h), (h, scale)


def _fused_rmsnorm_residual_bwd(config, residuals, grads):
    h, scale = residuals
    g_normed, g_h = grads
    dh, dscale = _norm_bwd_math(config, h, scale, g_normed, g_h)
    return dh.astype(h.dtype), dh.astype(h.dtype), dscale


_fused_rmsnorm_residual.defvjp(_fused_rmsnorm_residual_fwd,
                               _fused_rmsnorm_residual_bwd)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def fused_rmsnorm(x, scale, residual=None, eps=1e-6, out_dtype=None,
                  impl="auto", interpret: Optional[bool] = None,
                  block_rows=None):
    """Dispatching fused RMSNorm(+residual) tail: returns (normed, h).

    x: [..., D]; residual: same shape or None; scale: [D] (the flax
    RMSNorm "scale" param, f32). h = x + residual (the continuing
    residual stream; x itself when residual is None); normed =
    RMSNorm(h) in `out_dtype` (default: h's dtype).

    impl: "fused" forces the Pallas kernel, "reference" the lax path;
    "auto" picks the kernel on TPU, the reference elsewhere. The
    `CLOUD_TPU_FUSED_NORM` env var ("1"/"0") is the deployment A/B
    override and beats `impl`; a forced kernel runs in interpret mode
    off-TPU. Differentiable w.r.t. x, residual, and scale either way.
    """
    features = x.shape[-1]
    if scale.shape != (features,):
        raise ValueError(
            "scale must be [features] = ({},); got {}.".format(
                features, scale.shape))
    if residual is not None and residual.shape != x.shape:
        raise ValueError(
            "residual must match x's shape {}; got {}.".format(
                x.shape, residual.shape))
    env = os.environ.get("CLOUD_TPU_FUSED_NORM", "").strip()
    if env == "1":
        use_kernel = True
    elif env == "0":
        use_kernel = False
    elif impl == "fused":
        use_kernel = True
    elif impl == "reference":
        use_kernel = False
    else:
        use_kernel = jax.default_backend() == "tpu"
    if not use_kernel:
        return rmsnorm_residual_reference(x, scale, residual=residual,
                                          eps=eps, out_dtype=out_dtype)

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_rows is None:
        block_rows = int(os.environ.get("CLOUD_TPU_FUSED_NORM_BLOCK",
                                        _BLOCK_ROWS))
    if out_dtype is None:
        out_dtype = x.dtype if residual is None else jnp.promote_types(
            x.dtype, residual.dtype)

    lead = x.shape[:-1]
    rows = 1
    for dim in lead:
        rows *= dim
    block_rows = min(block_rows, max(rows, 1))
    rows_pad = -(-rows // block_rows) * block_rows
    # eps stays as passed (a static Python scalar — the config is a
    # hashable static kernel arg); a float(...) cast here would read
    # as a host sync to graftlint's jit-chain analysis.
    config = _NormConfig(eps=eps, block_rows=int(block_rows),
                         out_dtype=jnp.dtype(out_dtype).name,
                         interpret=bool(interpret))

    def fold(a):
        a = a.reshape(rows, features)
        if rows_pad != rows:
            # Zero rows: var = 0, rsqrt(eps) finite, output rows 0 —
            # sliced away below; pad/slice autodiff owns the edges.
            a = jnp.pad(a, ((0, rows_pad - rows), (0, 0)))
        return a

    w = scale.astype(jnp.float32)[None, :]
    if residual is None:
        normed, h = _fused_rmsnorm(config, fold(x), w)
    else:
        normed, h = _fused_rmsnorm_residual(config, fold(x),
                                            fold(residual), w)
    normed = normed[:rows].reshape(lead + (features,))
    h = h[:rows].reshape(lead + (features,))
    return normed, h


def fused_norm_cost(shape, dtype=jnp.bfloat16, with_residual=True):
    """Per-call flops / bytes-moved row for the telemetry gauges, via
    the jit cost-analysis hook on the lax reference (PR 6 idiom);
    bytes_moved is the fused single-pass traffic (x [+ residual] in,
    normed + h out, scale). Returns {"flops", "bytes_moved"}; never
    raises."""
    rows = 1
    for dim in shape[:-1]:
        rows *= dim
    features = shape[-1]
    n = float(rows * features)
    flops = 4.0 * n  # add, square, two scaled multiplies per element
    try:
        args = [jax.ShapeDtypeStruct(tuple(shape), dtype),
                jax.ShapeDtypeStruct((features,), jnp.float32)]
        if with_residual:
            fn = functools.partial(
                lambda x, s, r: rmsnorm_residual_reference(
                    x, s, residual=r))
            args.append(jax.ShapeDtypeStruct(tuple(shape), dtype))
        else:
            fn = rmsnorm_residual_reference
        analysis = jax.jit(fn).lower(*args).cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        flops = float(analysis.get("flops", flops) or flops)
    except Exception:
        pass
    itemsize = jnp.dtype(dtype).itemsize
    tensors = 4 if with_residual else 2  # in (+res), normed, h is x
    bytes_moved = float(tensors * n * itemsize + features * 4)
    return {"flops": flops, "bytes_moved": bytes_moved}


def record_cost_row(shape, dtype=jnp.bfloat16, with_residual=True,
                    iters=10):
    """Times the jitted fused tail at `shape` and feeds the telemetry
    kernel-cost row (`cloud_tpu_kernel_fused_norm_pct_peak` /
    `_bytes_moved`) — the bench/CI hook that turns the cost analysis
    into a tracked pct-of-peak metric. No-op (returns None) when
    telemetry is off; returns the per-call seconds otherwise."""
    import sys
    import time

    telemetry = sys.modules.get("cloud_tpu.monitoring.telemetry")
    if telemetry is None:
        return None
    tele = telemetry.get()
    if tele is None or not tele.active:
        return None
    import numpy as np

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(*shape), dtype)
    residual = jnp.asarray(rng.randn(*shape), dtype) if with_residual \
        else None
    scale = jnp.ones((shape[-1],), jnp.float32)

    @jax.jit
    def run(x, residual, scale):
        return fused_rmsnorm(x, scale, residual=residual)

    jax.block_until_ready(run(x, residual, scale))  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run(x, residual, scale)
    jax.block_until_ready(out)
    elapsed = (time.perf_counter() - t0) / max(iters, 1)
    cost = fused_norm_cost(shape, dtype, with_residual)
    tele.record_kernel_cost("fused_norm", cost["flops"],
                            cost["bytes_moved"], elapsed)
    return elapsed
