"""TPU compute kernels (Pallas) and their jnp reference implementations."""

from cloud_tpu.ops.attention import attention
from cloud_tpu.ops.attention import flash_attention
from cloud_tpu.ops.attention import mha_reference
from cloud_tpu.ops.fused_ce import lm_head_loss
from cloud_tpu.ops.fused_ce import lm_head_loss_reference

__all__ = ["attention", "flash_attention", "mha_reference",
           "lm_head_loss", "lm_head_loss_reference"]
