"""TPU compute kernels (Pallas) and their jnp reference implementations."""

from cloud_tpu.ops.attention import attention
from cloud_tpu.ops.attention import flash_attention
from cloud_tpu.ops.attention import mha_reference
from cloud_tpu.ops.fused_ce import lm_head_loss
from cloud_tpu.ops.fused_ce import lm_head_loss_reference
from cloud_tpu.ops.fused_mlp import fused_swiglu
from cloud_tpu.ops.fused_mlp import swiglu_reference
from cloud_tpu.ops.fused_norm import fused_rmsnorm
from cloud_tpu.ops.fused_norm import rmsnorm_residual_reference
from cloud_tpu.ops.paged_attention import paged_attention
from cloud_tpu.ops.paged_attention import paged_attention_cost
from cloud_tpu.ops.paged_attention import paged_attention_reference
from cloud_tpu.ops.paged_attention import paged_decode_attention

__all__ = ["attention", "flash_attention", "mha_reference",
           "lm_head_loss", "lm_head_loss_reference",
           "fused_swiglu", "swiglu_reference",
           "fused_rmsnorm", "rmsnorm_residual_reference",
           "paged_attention", "paged_attention_cost",
           "paged_attention_reference", "paged_decode_attention"]
