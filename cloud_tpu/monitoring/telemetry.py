"""graftscope: unified telemetry — metrics registry + lifecycle.

PRs 1-5 left the runtime with raw counters (`runtime.transfer_stats` /
`compile_stats`), a JSONL event log, the graftsan observer seam, and
jax-profiler wrappers — numbers, but no layer that turns them into
answerable questions ("where did this step's 40 ms go?", "what is
decode p99?"). This module is that layer:

- a **metrics registry**: Counter / Gauge / Histogram (exponential
  buckets with p50/p95/p99 readout) under one lock-per-metric design;
- **adapters**: a runtime observer (stacked NEXT TO graftsan through
  the widened `runtime.add_observer` seam) turns every H2D/D2H/compile
  record into counter movement; a span listener turns every completed
  graftscope span (monitoring/spans.py) into a latency observation —
  step latency, data wait, dispatch, D2H fetch — and `generate()` /
  beam / speculative feed a per-token decode-latency histogram (the
  precursor to serving p99); an MFU gauge derives model-flops-per-step
  (jit cost analysis) / chip peak;
- **lifecycle**: `CLOUD_TPU_TELEMETRY=1` makes Trainer entry points
  run under `env_scope()` — ambient enablement on first entry, a
  bounded-queue background flush (monitoring/export.py) per epoch, and
  a blocking flush at scope exit so `<dir>/trace.json`,
  `<dir>/metrics.prom` and `<dir>/telemetry.jsonl` are on disk when
  fit() returns.

Zero-cost discipline: with telemetry off nothing is installed — no
runtime observer, no span tracer, no thread; every integration point
is a None/env check (the graftsan seam contract, unchanged).

Env contract:
    CLOUD_TPU_TELEMETRY        1|on  -> Trainer entry points enable
    CLOUD_TPU_TELEMETRY_DIR    output directory (default ./telemetry)
    CLOUD_TPU_PEAK_TFLOPS      chip peak for the MFU gauge (default
                               197, the v5e bf16 peak bench.py uses)
"""

import bisect
import contextlib
import logging
import os
import threading

from cloud_tpu.monitoring import spans
from cloud_tpu.parallel import runtime

logger = logging.getLogger("cloud_tpu")

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "Telemetry",
           "enable", "disable", "get", "enabled", "env_enabled",
           "env_scope"]

#: v5e bf16 peak, TFLOPs — the same constant bench.py's pct_peak uses,
#: so the MFU gauge and the bench census agree on the denominator.
DEFAULT_PEAK_TFLOPS = 197.0

#: Span name -> histogram metric fed by the span listener.
SPAN_HISTOGRAMS = {
    "train_step": "cloud_tpu_step_latency_seconds",
    "data_wait": "cloud_tpu_data_wait_seconds",
    "dispatch": "cloud_tpu_dispatch_seconds",
    "d2h_fetch": "cloud_tpu_d2h_fetch_seconds",
    "checkpoint_snapshot": "cloud_tpu_checkpoint_snapshot_seconds",
    "async_reader_drain": "cloud_tpu_async_reader_drain_seconds",
    "decode": "cloud_tpu_decode_seconds",
    "serve_prefill": "cloud_tpu_serve_prefill_seconds",
    "serve_tick": "cloud_tpu_serve_tick_wall_seconds",
}

DECODE_TOKEN_HISTOGRAM = "cloud_tpu_decode_token_latency_seconds"
MFU_GAUGE = "cloud_tpu_mfu_pct_peak"

#: graftserve (serving/scheduler.py) metric names. The scheduler feeds
#: these through `telemetry.get().registry` under the same
#: zero-cost-when-off discipline as the decode hooks.
SERVE_REQUESTS_TOTAL = "cloud_tpu_serve_requests_total"
SERVE_TOKENS_TOTAL = "cloud_tpu_serve_tokens_total"
SERVE_REQUESTS_PER_SEC = "cloud_tpu_serve_requests_per_sec"
SERVE_QUEUE_DEPTH = "cloud_tpu_serve_queue_depth"
SERVE_ACTIVE_SLOTS = "cloud_tpu_serve_active_slots"
SERVE_TTFT_HISTOGRAM = "cloud_tpu_serve_ttft_seconds"
SERVE_TOKEN_HISTOGRAM = "cloud_tpu_serve_token_latency_seconds"
#: graftlens (PR 13) latency decomposition: queue wait (submit ->
#: admission pop) and KV-page reservation blocking time were previously
#: folded into TTFT; splitting them out is the direct input ROADMAP
#: item 4's predicted-TTFT admission needs, and the waiter gauge makes
#: PagePool backpressure visible instead of masquerading as prefill.
SERVE_QUEUE_WAIT_HISTOGRAM = "cloud_tpu_serve_queue_wait_seconds"
SERVE_RESERVE_WAIT_HISTOGRAM = "cloud_tpu_serve_reserve_wait_seconds"
SERVE_RESERVE_WAITERS = "cloud_tpu_serve_reserve_waiters"

#: graftshare (prefix cache + CoW pages + tick speculation) names.
#: Split TTFT: requests whose prompt hit the radix prefix cache prefill
#: only their suffix, so their TTFT distribution is a different
#: population from misses — one merged histogram would hide the win.
SERVE_TTFT_HIT_HISTOGRAM = "cloud_tpu_serve_ttft_hit_seconds"
SERVE_TTFT_MISS_HISTOGRAM = "cloud_tpu_serve_ttft_miss_seconds"
SERVE_PREFIX_HIT_RATE = "cloud_tpu_serve_prefix_hit_rate"
SERVE_PREFIX_PAGES_HELD = "cloud_tpu_serve_prefix_pages_held"
SERVE_PREFIX_EVICTIONS = "cloud_tpu_serve_prefix_evictions_total"
SERVE_PAGES_FREE = "cloud_tpu_serve_pages_free"
SERVE_PAGES_SHARED = "cloud_tpu_serve_pages_shared"
SERVE_COW_COPIES = "cloud_tpu_serve_cow_copies_total"
#: Accepted-token rate per verification round (accepted/proposed in
#: [0, 1]), shared by `generate_speculative` and the serving tick's
#: per-slot speculation (models/speculative.py observe_accept_rate).
SERVE_SPEC_ACCEPT_HISTOGRAM = "cloud_tpu_serve_spec_accepted_rate"

#: graftstorm (serving chaos) names. Fault/requeue/shed counters label
#: by taxonomy kind / shed reason via the `%s` suffix (the single-
#: registry renderer has no label support — the KERNEL gauge idiom).
#: The predicted-TTFT gauge is the admission controller's latest
#: estimate: what the NEXT admitted request is expected to wait.
SERVE_FAULTS_TOTAL = "cloud_tpu_serve_faults_total_%s"
SERVE_REQUEUES_TOTAL = "cloud_tpu_serve_requeues_total"
SERVE_SHED_TOTAL = "cloud_tpu_serve_shed_total_%s"
SERVE_PREDICTED_TTFT = "cloud_tpu_serve_predicted_ttft"
#: Always-on host prefill-latency histogram: the predicted-TTFT model
#: needs a live prefill estimate even when telemetry export is off.
SERVE_PREFILL_HISTOGRAM = "cloud_tpu_serve_prefill_seconds"

#: Chunked prefill (ROADMAP item 4 tail). Per-CHUNK prefill latency
#: replaces the whole-prefill p50 in the admission model when chunking
#: is on; the decode-gap histogram is the tick-to-tick commit interval
#: active slots actually experience (the p99 the interleave protects —
#: tick COMPUTE time alone cannot see a stalled tick loop). The pages
#: gauge counts pages reserved for prefills still in flight.
SERVE_PREFILL_CHUNK_HISTOGRAM = "cloud_tpu_serve_prefill_chunk_seconds"
SERVE_PREFILL_CHUNKS_TOTAL = "cloud_tpu_serve_prefill_chunks_total"
SERVE_DECODE_GAP_HISTOGRAM = "cloud_tpu_serve_decode_gap_seconds"
SERVE_PAGES_PREFILLING = "cloud_tpu_serve_pages_prefilling"

#: graftpack (ROADMAP item 3) names: the KV memory hierarchy. The
#: bytes gauge labels by tier via the `%s` suffix (hbm = pages the
#: pool holds x page_hbm_bytes, host = pages the host tier holds at
#: the same per-page cost); capacity-sessions is how many FULL-length
#: sequences the pool can hold resident at once — the gauge the int8
#: page mode exists to raise. Demote/promote counters accrue in PAGES
#: moved; digest failures count promote-time tree_digest mismatches
#: (typed HostTierCorrupt, entry dropped, request re-prefills).
SERVE_KV_BYTES = "cloud_tpu_serve_kv_bytes_%s"
SERVE_KV_CAPACITY_SESSIONS = "cloud_tpu_serve_kv_capacity_sessions"
SERVE_HOST_TIER_PAGES = "cloud_tpu_serve_host_tier_pages"
SERVE_PAGE_DEMOTES_TOTAL = "cloud_tpu_serve_page_demotes_total"
SERVE_PAGE_PROMOTES_TOTAL = "cloud_tpu_serve_page_promotes_total"
SERVE_DIGEST_FAILURES_TOTAL = "cloud_tpu_serve_digest_failures_total"

#: graftflex (elastic tick geometry) names. The slot-count gauge is
#: the CURRENT ladder rung; the resize counter labels by direction
#: (grow/shrink) via the `%s` suffix; the per-tick latency histogram
#: labels by the slot count the tick ran at — one histogram per rung,
#: so a goodput A/B never averages a 4-wide tick against a 32-wide
#: one (the mixed-width trap the geometry stamp closes).
SERVE_SLOT_COUNT = "cloud_tpu_serve_slot_count"
SERVE_RESIZES_TOTAL = "cloud_tpu_serve_resizes_total_%s"
SERVE_TICK_SECONDS = "cloud_tpu_serve_tick_seconds_slots_%s"

#: graftsweep (tuner/sweep.py) names. Counters accrue across every
#: sweep a process runs; the gauges hold the LATEST sweep's values.
#: `_warm_trials_total` counts reused-Trainer trials that finished
#: with zero new compiles — the shared-warm-cache win, pinned.
SWEEP_TRIALS_TOTAL = "cloud_tpu_sweep_trials_total"
SWEEP_TRIALS_PRUNED_TOTAL = "cloud_tpu_sweep_trials_pruned_total"
SWEEP_TRIALS_FAILED_TOTAL = "cloud_tpu_sweep_trials_failed_total"
SWEEP_FAULTS_TOTAL = "cloud_tpu_sweep_faults_total"
SWEEP_RESUMES_TOTAL = "cloud_tpu_sweep_resumes_total"
SWEEP_WARM_TRIALS_TOTAL = "cloud_tpu_sweep_warm_trials_total"
SWEEP_BEST_SCORE = "cloud_tpu_sweep_best_score"
SWEEP_COMPILE_SECONDS = "cloud_tpu_sweep_compile_seconds"

#: Per-kernel cost rows (ops/ Pallas kernels: "paged_attention",
#: "fused_norm"). Fed by `Telemetry.record_kernel_cost` from the jit
#: cost-analysis hook (the PR 6 MFU idiom, per-kernel): the serving
#: tick feeds paged_attention every tick with the measured tick
#: latency; `ops.fused_norm.record_cost_row` is the bench/CI feed for
#: the norm tail. `%s` is the kernel name.
KERNEL_PCT_PEAK_GAUGE = "cloud_tpu_kernel_%s_pct_peak"
KERNEL_BYTES_GAUGE = "cloud_tpu_kernel_%s_bytes_moved"


class Counter:
    """Monotonic counter (int)."""

    __slots__ = ("name", "_mu", "_value")

    def __init__(self, name):
        self.name = name
        self._mu = threading.Lock()
        self._value = 0

    def inc(self, delta=1):
        with self._mu:
            self._value += int(delta)

    @property
    def value(self):
        with self._mu:
            return self._value


class Gauge:
    """Last-write-wins float."""

    __slots__ = ("name", "_mu", "_value")

    def __init__(self, name):
        self.name = name
        self._mu = threading.Lock()
        self._value = 0.0

    def set(self, value):
        with self._mu:
            self._value = float(value)

    @property
    def value(self):
        with self._mu:
            return self._value


class Histogram:
    """Exponential-bucket histogram with percentile readout.

    Bucket upper bounds are `start * factor**i` for i in [0, buckets);
    observations above the last bound land in the +Inf bucket. The
    defaults (1 µs .. ~72 min at factor 2) cover every latency this
    framework measures — a step dispatch, a tunnel round trip, a cold
    compile — at ≤2x relative bucket error, which is what a p99 read
    off bucket interpolation inherits.
    """

    __slots__ = ("name", "_mu", "bounds", "_counts", "_sum", "_count",
                 "_max")

    def __init__(self, name, start=1e-6, factor=2.0, buckets=32):
        self.name = name
        self._mu = threading.Lock()
        bounds = []
        bound = float(start)
        for _ in range(int(buckets)):
            bounds.append(bound)
            bound *= float(factor)
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # [+Inf overflow last]
        self._sum = 0.0
        self._count = 0
        self._max = 0.0

    def observe(self, value, count=1):
        """Records `count` observations of `value` (a batched decode
        records its per-token latency once per generated token)."""
        value = float(value)
        idx = bisect.bisect_left(self.bounds, value)
        with self._mu:
            self._counts[idx] += count
            self._sum += value * count
            self._count += count
            if value > self._max:
                self._max = value

    @property
    def count(self):
        with self._mu:
            return self._count

    @property
    def sum(self):
        with self._mu:
            return self._sum

    def percentile(self, p):
        """Approximate p-th percentile (0-100) by linear interpolation
        inside the bucket holding that rank; 0.0 when empty. The +Inf
        bucket reports the largest observed value."""
        with self._mu:
            counts = list(self._counts)
            total = self._count
            largest = self._max
        if total <= 0:
            return 0.0
        rank = (p / 100.0) * total
        cumulative = 0
        for idx, bucket_count in enumerate(counts):
            previous = cumulative
            cumulative += bucket_count
            if cumulative >= rank and bucket_count:
                if idx >= len(self.bounds):
                    return largest
                upper = self.bounds[idx]
                lower = self.bounds[idx - 1] if idx else 0.0
                fraction = (rank - previous) / bucket_count
                return lower + (upper - lower) * min(fraction, 1.0)
        return largest

    def snapshot(self):
        with self._mu:
            counts = list(self._counts)
            total = self._count
            value_sum = self._sum
        return {
            "bounds": list(self.bounds),
            "counts": counts,
            "count": total,
            "sum": value_sum,
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
        }


class Registry:
    """Name-keyed metric store; get-or-create accessors."""

    def __init__(self):
        self._mu = threading.Lock()
        self._counters = {}
        self._gauges = {}
        self._histograms = {}

    def counter(self, name):
        with self._mu:
            metric = self._counters.get(name)
            if metric is None:
                metric = self._counters[name] = Counter(name)
            return metric

    def gauge(self, name):
        with self._mu:
            metric = self._gauges.get(name)
            if metric is None:
                metric = self._gauges[name] = Gauge(name)
            return metric

    def histogram(self, name, **kwargs):
        with self._mu:
            metric = self._histograms.get(name)
            if metric is None:
                metric = self._histograms[name] = Histogram(name,
                                                            **kwargs)
            return metric

    def snapshot(self):
        """Plain-data view for exporters: {"counters": {name: int},
        "gauges": {name: float}, "histograms": {name: {...}}}."""
        with self._mu:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {n: c.value for n, c in counters.items()},
            "gauges": {n: g.value for n, g in gauges.items()},
            "histograms": {n: h.snapshot()
                           for n, h in histograms.items()},
        }


class _RuntimeObserver:
    """The adapter on the widened runtime observer seam: every
    transfer/compile record becomes counter movement. Stacks with a
    graftsan Sanitizer through `runtime.add_observer` fanout."""

    def __init__(self, registry):
        self._h2d_transfers = registry.counter(
            "cloud_tpu_h2d_transfers_total")
        self._h2d_bytes = registry.counter("cloud_tpu_h2d_bytes_total")
        self._d2h_fetches = registry.counter(
            "cloud_tpu_d2h_fetches_total")
        self._d2h_bytes = registry.counter("cloud_tpu_d2h_bytes_total")
        self._traces = registry.counter("cloud_tpu_traces_total")
        self._compiles = registry.counter("cloud_tpu_compiles_total")
        self._cache_hits = registry.counter(
            "cloud_tpu_compile_cache_hits_total")
        self._cache_misses = registry.counter(
            "cloud_tpu_compile_cache_misses_total")

    def on_h2d(self, transfers, nbytes):
        self._h2d_transfers.inc(transfers)
        self._h2d_bytes.inc(nbytes)

    def on_d2h(self, nbytes, tree):
        self._d2h_fetches.inc(1)
        self._d2h_bytes.inc(nbytes)

    def on_compile(self, n_traces, n_compiles, cache_hits):
        self._traces.inc(n_traces)
        self._compiles.inc(n_compiles)
        self._cache_hits.inc(cache_hits)

    def on_cache_miss(self):
        self._cache_misses.inc(1)

    def on_epoch(self, epoch):
        pass

    def on_donation(self, args):
        pass


class Telemetry:
    """One enabled telemetry session: registry + tracer + exporters.

    Use the module-level `enable()`/`env_scope()` for the ambient
    singleton; direct construction is for tests that want an isolated
    instance.
    """

    def __init__(self, out_dir, peak_tflops=None):
        self.out_dir = str(out_dir)
        self.registry = Registry()
        self.tracer = None
        if peak_tflops is None:
            peak_tflops = float(os.environ.get(
                "CLOUD_TPU_PEAK_TFLOPS", DEFAULT_PEAK_TFLOPS))
        self.peak_flops = peak_tflops * 1e12
        self._observer = None
        self._worker = None
        self._exporters = ()
        self._step_flops = None
        self._active = False

    # -- lifecycle -----------------------------------------------------

    def enable(self):
        """Installs the span tracer + runtime observer and starts the
        background flush worker. Idempotent."""
        if self._active:
            return self
        os.makedirs(self.out_dir, exist_ok=True)
        self.tracer = spans.install()
        self.tracer.add_listener(self._on_span)
        self._observer = _RuntimeObserver(self.registry)
        runtime.add_observer(self._observer)
        # The headline series exist from t=0 (a textfile scrape between
        # enable and the first epoch still sees them).
        self.registry.gauge(MFU_GAUGE).set(0.0)
        self.registry.histogram("cloud_tpu_step_latency_seconds")
        self.registry.histogram(DECODE_TOKEN_HISTOGRAM)
        from cloud_tpu.monitoring import export
        self._exporters = export.default_exporters(self.out_dir)
        self._worker = export.FlushWorker(self._do_flush)
        self._active = True
        return self

    def disable(self):
        """Final flush, then tears every hook down. Idempotent."""
        if not self._active:
            return
        self._active = False
        worker, self._worker = self._worker, None
        if worker is not None:
            worker.close(flush=True)
        if self._observer is not None:
            runtime.remove_observer(self._observer)
            self._observer = None
        spans.uninstall()

    @property
    def active(self):
        return self._active

    # -- adapters ------------------------------------------------------

    def _on_span(self, name, t0_ns, dur_ns, tid):
        metric = SPAN_HISTOGRAMS.get(name)
        if metric is not None:
            self.registry.histogram(metric).observe(dur_ns / 1e9)

    def set_step_flops(self, flops):
        """Model flops for ONE train step (jit cost analysis), the MFU
        numerator. 0/None disables the gauge update."""
        self._step_flops = float(flops) if flops else None

    @property
    def step_flops(self):
        return self._step_flops

    def record_epoch(self, steps, examples, elapsed_secs):
        """Per-epoch rollup from the Trainer boundary: throughput
        counters, the MFU gauge, and one (lossy, non-blocking) flush."""
        if steps > 0:
            self.registry.counter("cloud_tpu_training_steps_total").inc(
                steps)
            self.registry.counter(
                "cloud_tpu_training_examples_total").inc(examples)
            elapsed_secs = max(float(elapsed_secs), 1e-9)
            self.registry.gauge("cloud_tpu_steps_per_sec").set(
                steps / elapsed_secs)
            if self._step_flops:
                flops_per_sec = self._step_flops * steps / elapsed_secs
                self.registry.gauge(MFU_GAUGE).set(
                    100.0 * flops_per_sec / self.peak_flops)
        self.flush()

    def record_kernel_cost(self, kernel, flops, bytes_moved,
                           elapsed_secs=None):
        """Per-kernel cost row: bytes-moved always, pct-of-peak when
        the caller knows the wall time one call took (MFU math, same
        peak denominator as the step gauge). `kernel` is the row name
        ("paged_attention", "fused_norm"); flops/bytes come from the
        jit cost-analysis hook (ops.paged_attention_cost /
        ops.fused_norm.fused_norm_cost)."""
        self.registry.gauge(KERNEL_BYTES_GAUGE % kernel).set(
            float(bytes_moved))
        if flops and elapsed_secs and elapsed_secs > 0:
            self.registry.gauge(KERNEL_PCT_PEAK_GAUGE % kernel).set(
                100.0 * (float(flops) / float(elapsed_secs))
                / self.peak_flops)

    def observe_decode(self, n_tokens, elapsed_secs):
        """Per-token decode latency: one observation per generated
        token at the call's mean per-token latency (all tokens of one
        scan share their dispatch's wall time)."""
        n_tokens = int(n_tokens)
        if n_tokens <= 0:
            return
        self.registry.histogram(DECODE_TOKEN_HISTOGRAM).observe(
            float(elapsed_secs) / n_tokens, count=n_tokens)

    # -- export --------------------------------------------------------

    def flush(self, wait=False):
        """Requests an export pass on the background worker. Non-wait
        requests are lossy when one is already queued (coalesced);
        wait=True blocks until a full pass completed."""
        worker = self._worker
        if worker is None:
            self._do_flush()
            return
        worker.request(wait=wait)

    def _do_flush(self):
        for exporter in self._exporters:
            try:
                exporter.export(self)
            except Exception:
                logger.debug("telemetry exporter %r failed",
                             exporter, exc_info=True)


# -- ambient singleton + env contract -----------------------------------

_telemetry = None
_enable_lock = threading.Lock()


def env_enabled():
    """The CLOUD_TPU_TELEMETRY env contract (same truthiness grammar
    as CLOUD_TPU_SANITIZE)."""
    value = os.environ.get("CLOUD_TPU_TELEMETRY", "").strip().lower()
    return value not in ("", "0", "off", "false", "none")


def enable(out_dir=None):
    """Enables the ambient telemetry singleton (idempotent). `out_dir`
    defaults to CLOUD_TPU_TELEMETRY_DIR, then ./telemetry."""
    global _telemetry
    with _enable_lock:
        if _telemetry is None:
            if out_dir is None:
                out_dir = (os.environ.get("CLOUD_TPU_TELEMETRY_DIR")
                           or os.path.join(os.getcwd(), "telemetry"))
            _telemetry = Telemetry(out_dir)
        return _telemetry.enable()


def disable():
    """Tears the ambient singleton down (test isolation)."""
    global _telemetry
    with _enable_lock:
        tele, _telemetry = _telemetry, None
    if tele is not None:
        tele.disable()


def get():
    """The ambient Telemetry, or None when disabled."""
    return _telemetry


def enabled():
    return _telemetry is not None and _telemetry.active


@contextlib.contextmanager
def env_scope():
    """Library entry-point scope (Trainer.fit/evaluate): enables the
    ambient singleton when CLOUD_TPU_TELEMETRY asks for it, and
    guarantees a completed (blocking) flush at scope exit so the
    trace/textfile artifacts exist the moment the entry point returns.
    Enablement is ambient, not scoped — nested fits reuse the same
    session and tear nothing down (use `disable()` for that)."""
    if not env_enabled():
        yield None
        return
    tele = enable()
    try:
        yield tele
    finally:
        tele.flush(wait=True)
