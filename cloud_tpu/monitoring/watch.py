"""graftwatch: fleet health probes + hang flight recorder.

The reference framework's whole value is watching a remote cloud job
you can't ssh into (CAIP submit + the Stackdriver exporter); our own
bench history shows the blind spot — the round-5 tunnel outage left
`stale: true` records and 11 hours of unanswered probes, and a hung
`fit()` died only at an outer 30-minute timeout with nothing saying
WHERE it hung. graftwatch is the fleet-health layer over graftscope:

- a **heartbeat watchdog**: the Trainer's step loop beats a monitor
  thread; when no step (or boundary) progress arrives within the stall
  deadline, the monitor snapshots every thread's stack, runs the
  shared deadline-bounded backend probe (`runtime.probe_backend`, the
  same probe bench.py uses), writes a `blackbox.json` flight-recorder
  artifact, and converts the hang into a typed
  `runtime.BackendUnavailable` delivered to the training thread within
  seconds — not a 30-minute outer timeout;
- **liveness gauges**: while watching, every poll tick exports
  `cloud_tpu_watch_alive` / `cloud_tpu_watch_heartbeat_age_seconds` /
  `cloud_tpu_watch_last_step_age_seconds` / `cloud_tpu_watch_last_step`
  through the graftscope registry (when telemetry is enabled), so a
  fleet collector can see a straggler BEFORE it becomes a corpse;
- a **flight recorder**: `write_blackbox()` dumps all-thread stacks
  (structured + a raw `faulthandler` section), the graftscope span
  tail, the transfer/compile counter snapshots, any graftsan site
  table, and the tail of the JSONL job-event log — every hang or crash
  leaves a diagnosable artifact.

Zero-cost discipline (the graftsan/graftscope seam contract): nothing
is installed unless `CLOUD_TPU_WATCH` asks for it — no thread, no
hook; `heartbeat()`/`notify_step()` are one global load + None check
when disabled, and with the env unset `Trainer.fit()` installs zero
watch machinery (test-pinned).

Delivery semantics, honestly stated: the stall error is delivered via
`PyThreadState_SetAsyncExc`, which interrupts Python-level stalls (a
dispatch spinning in a retry loop, a feeder deadlock) within one
bytecode boundary. A thread wedged inside a single C call (a truly
hung XLA dispatch) cannot be interrupted from userspace — for that
case the guarantee is the ARTIFACT (blackbox + gauges + job event),
plus the opt-in `CLOUD_TPU_WATCH_FATAL=1` escalation: one full
deadline after the stall fired with still no heartbeat, the process
exits 70 so the fleet scheduler can reschedule in seconds instead of
waiting out the outer timeout.

Env contract:
    CLOUD_TPU_WATCH                  1|on -> Trainer entry points watch
    CLOUD_TPU_WATCH_DEADLINE         stall deadline, seconds (60)
    CLOUD_TPU_WATCH_STARTUP_DEADLINE pre-first-step deadline (600 —
                                     cold compiles are not stalls)
    CLOUD_TPU_WATCH_INTERVAL         monitor poll period (deadline/4,
                                     capped at 5s)
    CLOUD_TPU_WATCH_DIR              blackbox.json directory (default
                                     CLOUD_TPU_TELEMETRY_DIR, then
                                     ./telemetry)
    CLOUD_TPU_WATCH_PROBE            0 -> skip the backend probe on
                                     stall (tests)
    CLOUD_TPU_WATCH_PROBE_DEADLINE   probe subprocess bound (20s)
    CLOUD_TPU_WATCH_FATAL            1 -> exit(70) one deadline after
                                     an undeliverable stall error
"""

import contextlib
import ctypes
import faulthandler
import json
import logging
import os
import socket
import sys
import tempfile
import threading
import time
import traceback

from cloud_tpu.monitoring import spans
from cloud_tpu.parallel import runtime

logger = logging.getLogger("cloud_tpu")

__all__ = ["Watchdog", "write_blackbox", "install", "uninstall",
           "current", "enabled", "env_enabled", "env_scope",
           "heartbeat", "notify_step", "notify_reentry", "check",
           "rewatch"]

#: Spans / job events kept in the blackbox tail.
BLACKBOX_SPAN_TAIL = 100
BLACKBOX_EVENT_TAIL = 25

_EXIT_FATAL = 70


def _env_float(key, default):
    try:
        return float(os.environ.get(key, default))
    except (TypeError, ValueError):
        return default


def env_enabled():
    """The CLOUD_TPU_WATCH env contract (same truthiness grammar as
    CLOUD_TPU_TELEMETRY / CLOUD_TPU_SANITIZE)."""
    value = os.environ.get("CLOUD_TPU_WATCH", "").strip().lower()
    return value not in ("", "0", "off", "false", "none")


def _process_index():
    """This process's index: the CLOUD_TPU_PROCESS_ID env contract
    first, a jax that is ALREADY imported second, else 0 — never an
    import, so the disabled path stays jax-free."""
    value = os.environ.get("CLOUD_TPU_PROCESS_ID")
    if value is not None:
        try:
            return int(value)
        except ValueError:
            return 0
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:
            return 0
    return 0


def _async_raise(tid, exc_type):
    """Schedules `exc_type` in thread `tid` (CPython only). Returns
    True when exactly one thread was targeted."""
    set_async = getattr(ctypes.pythonapi, "PyThreadState_SetAsyncExc",
                        None)
    if set_async is None:
        return False
    res = set_async(ctypes.c_ulong(tid), ctypes.py_object(exc_type))
    if res > 1:  # never happens for a valid ident; undo per the docs
        set_async(ctypes.c_ulong(tid), None)
        return False
    return res == 1


def _thread_stacks(stuck_tid=None):
    """Structured all-thread stacks from sys._current_frames()."""
    threads = {t.ident: t for t in threading.enumerate()}
    entries = []
    for tid, frame in sys._current_frames().items():
        thread = threads.get(tid)
        stack = [{"file": f.filename, "line": f.lineno,
                  "function": f.name, "code": f.line or ""}
                 for f in traceback.extract_stack(frame)]
        entries.append({
            "tid": tid,
            "name": thread.name if thread is not None
            else "thread-{}".format(tid),
            "daemon": bool(thread.daemon) if thread is not None else None,
            "stuck": tid == stuck_tid,
            "stack": stack,
        })
    # Stuck thread first: the artifact's reader wants the culprit on
    # top, not buried under daemon helpers.
    entries.sort(key=lambda e: (not e["stuck"], e["name"]))
    return entries


def _faulthandler_text():
    """The raw faulthandler all-thread dump (the signal-safe truth the
    structured stacks are derived next to, kept verbatim because it is
    the format every postmortem tool already reads)."""
    try:
        with tempfile.TemporaryFile(mode="w+") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
            f.seek(0)
            return f.read()
    except Exception:
        return None


def _sanitizer_sites():
    """Any stacked graftsan observer's site table (duck-typed off the
    runtime observer stack, the JsonlExporter recipe)."""
    for observer in runtime.observers():
        site_counts = getattr(observer, "site_counts", None)
        if callable(site_counts):
            try:
                return site_counts()
            except Exception:
                return None
    return None


def _job_events_tail(limit=BLACKBOX_EVENT_TAIL):
    """Last `limit` parseable records of the JSONL job-event log
    (CLOUD_TPU_EVENT_LOG), reading only the file's final 64KB so a
    week-long log costs nothing. Torn lines are skipped — this runs
    while a writer may be mid-append."""
    path = os.environ.get("CLOUD_TPU_EVENT_LOG")
    if not path:
        return []
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 65536))
            data = f.read().decode("utf-8", errors="replace")
    except OSError:
        return []
    lines = data.splitlines()
    if size > 65536 and lines:
        lines = lines[1:]  # first line may be torn by the seek
    records = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            continue
    return records[-limit:]


def write_blackbox(path, reason, stuck_tid=None, last_step=None,
                   last_step_age=None, heartbeat_age=None, probe=None,
                   error=None, stacks=None):
    """Writes the flight-recorder artifact to `path` (atomic
    tmp+rename) and returns the path.

    The artifact answers the questions a dead job can't: WHERE every
    thread was (structured stacks + raw faulthandler text, stuck
    thread first), WHAT the runtime had done (transfer/compile counter
    snapshots, graftsan site table), WHAT the host was doing around
    the incident (graftscope span tail), and WHAT the job had reported
    (JSONL event-log tail). Collection is best-effort per section — a
    failing source yields a null field, never a missing artifact.
    """
    record = {
        "format": "cloud_tpu.blackbox.v1",
        "reason": reason,
        "time": time.time(),
        "monotonic": time.monotonic(),
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "process_index": _process_index(),
        "last_step": last_step,
        "last_step_age_seconds": last_step_age,
        "heartbeat_age_seconds": heartbeat_age,
        "probe": probe,
        "error": error,
        "threads": stacks if stacks is not None
        else _thread_stacks(stuck_tid),
        "faulthandler": _faulthandler_text(),
        "transfer_stats": runtime.transfer_stats(),
        "compile_stats": runtime.compile_stats(),
        "sanitizer_sites": _sanitizer_sites(),
        "job_events_tail": _job_events_tail(),
    }
    tracer = spans.current_tracer()
    if tracer is not None:
        events = tracer.events()[-BLACKBOX_SPAN_TAIL:]
        record["spans_tail"] = [
            {"name": name, "tid": tid, "t0_ns": t0, "dur_ns": dur}
            for name, tid, t0, dur in events]
        record["spans_dropped"] = tracer.dropped()
    else:
        record["spans_tail"] = []
        record["spans_dropped"] = 0
    directory = os.path.dirname(path)
    if directory:
        os.makedirs(directory, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


class Watchdog:
    """Heartbeat monitor: stall detection, blackbox dump, typed error.

    The training thread (whoever calls `start()`) beats via
    `beat()`/`notify_step()`; a daemon monitor thread polls the beat
    age. Before the first completed step the startup deadline applies
    (a cold compile is not a stall); after it, the stall deadline.
    On stall the monitor — running OUTSIDE the hung thread — captures
    stacks, probes the backend through `runtime.probe_backend`, writes
    `blackbox.json`, logs a `graftwatch` job event, and schedules a
    `runtime.BackendUnavailable` in the watched thread. The incident
    LATCHES: once fired, `check()` raises the pending error even if a
    glacial step eventually completes — a deadline sized below the
    slowest legitimate step is a config bug worth dying loudly on.
    """

    def __init__(self, stall_deadline=None, startup_deadline=None,
                 poll_interval=None, probe=None, probe_deadline=None,
                 out_dir=None, fatal=None):
        if stall_deadline is None:
            stall_deadline = _env_float("CLOUD_TPU_WATCH_DEADLINE", 60.0)
        if startup_deadline is None:
            startup_deadline = _env_float(
                "CLOUD_TPU_WATCH_STARTUP_DEADLINE",
                max(600.0, stall_deadline))
        if poll_interval is None:
            poll_interval = _env_float(
                "CLOUD_TPU_WATCH_INTERVAL",
                min(max(stall_deadline / 4.0, 0.05), 5.0))
        if probe is None:
            probe = os.environ.get("CLOUD_TPU_WATCH_PROBE", "1") != "0"
        if probe_deadline is None:
            probe_deadline = _env_float(
                "CLOUD_TPU_WATCH_PROBE_DEADLINE", 20.0)
        if out_dir is None:
            out_dir = (os.environ.get("CLOUD_TPU_WATCH_DIR")
                       or os.environ.get("CLOUD_TPU_TELEMETRY_DIR")
                       or os.path.join(os.getcwd(), "telemetry"))
        if fatal is None:
            fatal = os.environ.get("CLOUD_TPU_WATCH_FATAL", "") == "1"
        self.stall_deadline = float(stall_deadline)
        self.startup_deadline = float(startup_deadline)
        self.poll_interval = float(poll_interval)
        self.probe = bool(probe)
        self.probe_deadline = float(probe_deadline)
        self.out_dir = str(out_dir)
        self.fatal = bool(fatal)
        self.blackbox_path = os.path.join(self.out_dir, "blackbox.json")
        # Beat state: plain attribute writes (atomic under the GIL) so
        # a beat from the hot loop takes no lock.
        now = time.monotonic()
        self._last_beat = now
        self._last_step_time = now
        self._step_count = 0
        # True until the first completed step of the CURRENT (re)entry
        # into the watched scope: the generous startup deadline covers
        # compile/restore; the tight stall deadline takes over once
        # steps flow. `notify_reentry` re-arms it so a graftguard
        # resume replaying restore+rebuild isn't judged by the step
        # deadline (ISSUE 9 satellite: STARTUP_DEADLINE per (re)entry,
        # not only the first).
        self._in_startup = True
        self._started = now
        self._watched_tid = None
        self._pending = None
        self._fired = False
        self._fired_at = None
        self._async_delivered = False
        self._stalls = 0
        self._stop = threading.Event()
        self._thread = None
        self._crash_dumped = False
        self._step_exported = False

    # -- the watched side ----------------------------------------------

    def start(self, watched_tid=None):
        """Starts the monitor thread, watching `watched_tid` (default:
        the calling thread). Idempotent."""
        if self._thread is not None:
            return self
        if watched_tid is None:
            watched_tid = threading.get_ident()
        self._watched_tid = watched_tid
        now = time.monotonic()
        self._last_beat = now
        self._last_step_time = now
        self._in_startup = True
        self._started = now
        self._stop.clear()
        self._step_exported = False
        self._thread = threading.Thread(
            target=self._run, name="cloud-tpu-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Stops the monitor thread (joined; idempotent)."""
        thread, self._thread = self._thread, None
        if thread is not None:
            self._stop.set()
            thread.join(timeout=10)

    def beat(self):
        """One liveness heartbeat (boundary work, eval batches)."""
        self._last_beat = time.monotonic()

    def rewatch(self, tid=None):
        """Re-aims the async-raise target at `tid` (default: the
        calling thread) and beats. A loop that adopts an installed
        watchdog — graftserve's tick thread — calls this once so a
        stall interrupts the thread that is actually stuck, not
        whichever thread ran install()."""
        self._watched_tid = (threading.get_ident() if tid is None
                             else tid)
        self._last_beat = time.monotonic()

    def notify_step(self, step=None):
        """One COMPLETED train step: beats and advances the step
        census the blackbox reports as `last_step`."""
        now = time.monotonic()
        if step is not None:
            self._step_count = int(step)
        else:
            self._step_count += 1
        self._in_startup = False
        self._last_step_time = now
        self._last_beat = now
        if not self._step_exported:
            # The watch scope wraps the telemetry scope, so the
            # registry wasn't active yet at start(); the first
            # completed step is the earliest deterministic moment it
            # is. One-time, so runs shorter than the poll interval
            # still stamp `alive` for the fleet collector.
            self._step_exported = True
            self._export_gauges(now, 0.0)

    def check(self):
        """Raises the pending BackendUnavailable, if a stall fired.
        The deterministic delivery point for threads the async raise
        could not reach (called at scope exit and safe anywhere)."""
        pending = self._pending
        if pending is not None and not self._async_delivered:
            self._pending = None
            raise pending

    def notify_reentry(self):
        """Re-arms the watchdog for a fresh (re)entry into the watched
        scope — graftguard calls this before every resume attempt.

        Resets the beat clocks and clears any latched stall so the
        generous STARTUP deadline (not the tight stall deadline)
        governs until the resumed run completes its first step: the
        re-entry legitimately spends that window on restore, rebuild,
        and (cold-cache worst case) recompile.
        """
        now = time.monotonic()
        self._last_beat = now
        self._last_step_time = now
        self._in_startup = True
        self._pending = None
        self._fired = False
        self._fired_at = None
        self._async_delivered = False
        self._crash_dumped = False

    def take_pending(self):
        """Removes and returns the pending error (or None) — the scope
        wrapper swaps the bare async-raised class for this rich
        instance."""
        pending, self._pending = self._pending, None
        return pending

    @property
    def last_step(self):
        return self._step_count

    @property
    def stalls(self):
        return self._stalls

    @property
    def fired(self):
        return self._fired

    def record_crash(self, exc):
        """Writes a crash blackbox for an exception escaping the
        watched scope (once per incident; a stall that already dumped
        does not get overwritten by its own propagating error)."""
        if self._fired or self._crash_dumped:
            return None
        self._crash_dumped = True
        now = time.monotonic()
        try:
            return write_blackbox(
                self.blackbox_path,
                "crash",
                stuck_tid=self._watched_tid,
                last_step=self._step_count,
                last_step_age=now - self._last_step_time,
                heartbeat_age=now - self._last_beat,
                error="{}: {}".format(type(exc).__name__, exc))
        except Exception:
            logger.exception("graftwatch: crash blackbox write failed")
            return None

    # -- the monitor side ----------------------------------------------

    def _run(self):
        while not self._stop.wait(self.poll_interval):
            now = time.monotonic()
            beat_age = now - self._last_beat
            self._export_gauges(now, beat_age)
            if self._fired:
                if (self.fatal and self._fired_at is not None
                        and now - self._fired_at > self.stall_deadline
                        and time.monotonic() - self._last_beat
                        > self.stall_deadline):
                    # The error could not be delivered and the thread
                    # never recovered: the artifact is on disk, exit
                    # loudly so the scheduler reschedules in seconds.
                    logger.error(
                        "graftwatch: stall error undeliverable for "
                        "%.0fs past the deadline; exiting %d "
                        "(CLOUD_TPU_WATCH_FATAL=1).",
                        now - self._fired_at, _EXIT_FATAL)
                    os._exit(_EXIT_FATAL)
                continue
            deadline = (self.startup_deadline if self._in_startup
                        else self.stall_deadline)
            if beat_age > deadline:
                self._on_stall(beat_age, deadline)

    def _export_gauges(self, now, beat_age):
        """Liveness gauges through the graftscope registry, when a
        telemetry session is active (sys.modules.get: watching must
        not IMPORT telemetry into a process that never enabled it)."""
        telemetry = sys.modules.get("cloud_tpu.monitoring.telemetry")
        if telemetry is None:
            return
        try:
            tele = telemetry.get()
            if tele is None or not tele.active:
                return
            reg = tele.registry
            reg.gauge("cloud_tpu_watch_alive").set(
                0.0 if self._fired else 1.0)
            reg.gauge("cloud_tpu_watch_heartbeat_age_seconds").set(
                beat_age)
            reg.gauge("cloud_tpu_watch_last_step_age_seconds").set(
                now - self._last_step_time)
            reg.gauge("cloud_tpu_watch_last_step").set(self._step_count)
        except Exception:  # a metrics sink must never kill the monitor
            logger.debug("graftwatch gauge export failed", exc_info=True)

    def _on_stall(self, beat_age, deadline):
        step_age = time.monotonic() - self._last_step_time
        # Stacks FIRST (closest to the stall), probe second (it can
        # take probe_deadline seconds), artifact third with both.
        stacks = _thread_stacks(self._watched_tid)
        probe = None
        if self.probe:
            ok, diagnosis = runtime.probe_backend(
                deadline=self.probe_deadline)
            probe = {"ok": ok, "diagnosis": diagnosis}
        if probe is None:
            verdict = "no backend probe run"
        elif probe["ok"]:
            verdict = ("backend probe HEALTHY ({}) — the hang is "
                       "host-side (deadlocked feeder, wedged dispatch "
                       "thread)".format(probe["diagnosis"]))
        else:
            verdict = "backend probe FAILED: {}".format(
                probe["diagnosis"])
        message = (
            "No training progress for {:.1f}s (deadline {:.1f}s; last "
            "completed step {}, {:.1f}s ago). {}. Flight recorder: "
            "{}".format(beat_age, deadline, self._step_count, step_age,
                        verdict, self.blackbox_path))
        path = None
        try:
            path = write_blackbox(
                self.blackbox_path, "stall",
                stuck_tid=self._watched_tid,
                last_step=self._step_count,
                last_step_age=step_age, heartbeat_age=beat_age,
                probe=probe, error=message, stacks=stacks)
        except Exception:
            logger.exception("graftwatch: blackbox write failed")
        try:
            from cloud_tpu.utils import events
            events.log_job_event("graftwatch", {
                "event": "stall", "heartbeat_age_seconds": beat_age,
                "deadline_seconds": deadline,
                "last_step": self._step_count,
                "probe": probe, "blackbox": path})
        except Exception:
            logger.debug("graftwatch job event failed", exc_info=True)
        error = runtime.BackendUnavailable(
            message, diagnosis=probe.get("diagnosis") if probe else None,
            deadline=deadline, blackbox=path)
        # Pending BEFORE the latch flips: anyone who observes
        # `fired` must be able to collect the error via check()/
        # take_pending(). (_run is the only caller, so there is no
        # re-entry hazard in latching last.)
        self._pending = error
        self._stalls += 1
        self._fired = True
        self._fired_at = time.monotonic()
        logger.error("graftwatch: %s", message)
        if self._watched_tid is not None:
            self._async_delivered = _async_raise(
                self._watched_tid, runtime.BackendUnavailable)


# -- module seam (the None-check discipline) ----------------------------

_watchdog = None


def install(**kwargs):
    """Installs (and starts) the ambient watchdog. Idempotent when one
    is already running and no kwargs are given."""
    global _watchdog
    if _watchdog is None:
        _watchdog = Watchdog(**kwargs).start()
    return _watchdog


def uninstall():
    """Stops and removes the ambient watchdog (returns it, or None)."""
    global _watchdog
    previous, _watchdog = _watchdog, None
    if previous is not None:
        previous.stop()
    return previous


def current():
    return _watchdog


def enabled():
    return _watchdog is not None


def heartbeat():
    """One liveness beat (boundary/eval work). One global load + None
    check when disabled."""
    w = _watchdog
    if w is not None:
        w.beat()


def notify_step(step=None):
    """One completed train step. One global load + None check when
    disabled."""
    w = _watchdog
    if w is not None:
        w.notify_step(step)


def check():
    """Raises a pending stall error, if the watchdog latched one."""
    w = _watchdog
    if w is not None:
        w.check()


def rewatch(tid=None):
    """Hands the installed watchdog to the calling thread (async-raise
    target). No-op when disabled."""
    w = _watchdog
    if w is not None:
        w.rewatch(tid)


def notify_reentry():
    """Re-arms the installed watchdog for a resume attempt (startup
    deadline + cleared stall latch). No-op when disabled."""
    w = _watchdog
    if w is not None:
        w.notify_reentry()


@contextlib.contextmanager
def env_scope():
    """Trainer entry-point scope: installs the watchdog when
    CLOUD_TPU_WATCH asks for it, enables faulthandler (a hard crash
    dumps all threads to stderr), swaps the bare async-raised
    BackendUnavailable class for the rich latched instance, writes a
    crash blackbox for any other escaping exception, and tears the
    watchdog down on exit. Nested entry points (fit's validation
    evaluate) see the already-installed watchdog and change nothing.
    """
    if not env_enabled():
        yield None
        return
    if _watchdog is not None:  # nested entry point: ride the outer one
        yield _watchdog
        return
    try:
        faulthandler.enable()
    except Exception:  # exotic platforms without stderr fds
        pass
    w = install()
    try:
        try:
            yield w
            w.check()
        except runtime.BackendUnavailable as e:
            pending = w.take_pending()
            if pending is not None and pending is not e:
                raise pending from e
            raise
        except BaseException as e:
            w.record_crash(e)
            raise
    finally:
        uninstall()
