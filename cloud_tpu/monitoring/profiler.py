"""Tracing/profiling subsystem: JAX profiler hooks.

The reference has no dedicated tracing subsystem (SURVEY §5 — its
closest analogues are TensorBoard event logs reused as a metric
transport and the 10s periodic metric exporter). The TPU-native build
gets a real one: thin, dependency-free wrappers over the JAX/XLA
profiler (device traces viewable in TensorBoard/Perfetto, with MXU
utilization and HBM analysis on TPU) plus a Trainer callback that
captures selected epochs, and step annotations that show up as named
spans in the trace.
"""

import contextlib
import logging

import jax

from cloud_tpu.training.callbacks import Callback


def start_server(port=9012):
    """Starts the profiler server for on-demand remote capture
    (`tensorboard --logdir` "capture profile" button or
    `jax.profiler.start_trace` from another process)."""
    return jax.profiler.start_server(port)


def _profile_options(host_tracer_level=None, python_tracer_level=None):
    """A `jax.profiler.ProfileOptions` with the given tracer levels, or
    None on jax versions that predate the class (feature-gated: the
    options are a tuning knob, never a requirement)."""
    options_cls = getattr(jax.profiler, "ProfileOptions", None)
    if options_cls is None:
        return None
    options = options_cls()
    if host_tracer_level is not None:
        options.host_tracer_level = host_tracer_level
    if python_tracer_level is not None:
        options.python_tracer_level = python_tracer_level
    return options


def _start_trace(log_dir, options):
    """start_trace with `options` when both the options object and the
    `profiler_options` kwarg exist; plain start_trace otherwise. Some
    jax versions ship ProfileOptions but not the kwarg (or vice versa),
    so the TypeError fallback covers the half-feature case too."""
    if options is not None:
        try:
            jax.profiler.start_trace(log_dir, profiler_options=options)
            return
        except TypeError:
            pass
    jax.profiler.start_trace(log_dir)


@contextlib.contextmanager
def trace(log_dir, host_tracer_level=2, python_tracer_level=1):
    """Context manager capturing a device+host trace into `log_dir`.

    The artifact lands under `<log_dir>/plugins/profile/<run>` in the
    TensorBoard profile-plugin layout. Tracer levels apply only on jax
    versions whose profiler exposes ProfileOptions; older/newer ones
    fall back to a plain `start_trace` instead of raising.
    """
    _start_trace(log_dir, _profile_options(host_tracer_level,
                                           python_tracer_level))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name):
    """Named span inside a trace (shows as a labeled region); usable as
    decorator or context manager."""
    return jax.profiler.TraceAnnotation(name)


def device_memory_profile(path=None):
    """Snapshot of per-device memory (pprof format). Returns the bytes,
    and writes them to `path` when given."""
    data = jax.profiler.device_memory_profile()
    if path is not None:
        with open(path, "wb") as f:
            f.write(data)
    return data


class ProfilerCallback(Callback):
    """Traces selected training epochs into `log_dir`.

    By default profiles epoch 1 only (epoch 0 pays the jit compile, so
    its trace is mostly compilation): the standard "skip the warmup
    epoch" recipe.
    """

    def __init__(self, log_dir, epochs=(1,)):
        self.log_dir = log_dir
        self.epochs = set(epochs)
        self._active = False
        self._run_epochs = self.epochs

    def on_train_begin(self):
        # Per-run view: never mutate the configured epochs, so a reused
        # callback instance re-evaluates the fallback for each fit().
        self._run_epochs = self.epochs
        planned = getattr(self.trainer, "planned_epochs", None)
        start = getattr(self.trainer, "initial_epoch", 0)
        if planned is not None and not any(start <= e < planned
                                           for e in self.epochs):
            # E.g. the default epochs=(1,) with fit(epochs=1) (only
            # epoch 0 runs) or a resumed fit(initial_epoch=4) that
            # starts past every requested epoch. Trace the first epoch
            # THIS fit will actually run rather than silently producing
            # nothing.
            logging.getLogger("cloud_tpu").warning(
                "ProfilerCallback: none of the requested epochs %s will "
                "run (fit runs epochs [%d, %d)); profiling epoch %d "
                "instead.", sorted(self.epochs), start, planned, start)
            self._run_epochs = {start}

    def on_epoch_begin(self, epoch):
        if epoch in self._run_epochs and jax.process_index() == 0:
            _start_trace(self.log_dir, _profile_options())
            self._active = True

    def on_epoch_end(self, epoch, logs):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False

    def on_train_end(self, history):
        if self._active:  # interrupted epoch (e.g. EarlyStopping)
            jax.profiler.stop_trace()
            self._active = False
