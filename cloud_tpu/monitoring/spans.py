"""graftscope span tracer: nested, thread-aware host wall-time spans.

The jax profiler answers "what did the DEVICE do"; nothing answered
"where did the HOST's step wall time go" — data wait vs dispatch vs the
coalesced D2H fetch vs checkpoint snapshot. This module is that layer:
monotonic-ns spans recorded per thread into one bounded in-process
buffer, exported as Chrome trace-event JSON (the `{"traceEvents": []}`
format Perfetto and chrome://tracing load directly). Nesting needs no
parent pointers: complete ("ph":"X") events on one thread nest by time
containment, exactly how the viewers render them.

Zero-cost discipline (same seam shape as the graftsan observer): the
module-level tracer is None until `install()`; every record helper is
one global load + None check when disabled — nothing is wrapped,
patched, or allocated. The Trainer additionally gates its generator
wrapping on `enabled()` so the disabled hot loop is byte-identical to
the pre-graftscope one.

Span names are a contract (docs/training/README.md span table, the CI
telemetry smoke, and the telemetry histograms all key on them):

    step                  one epoch's step-loop section
    boundary              one epoch's end-of-epoch host work
    train_step            one step: data wait + dispatch + log append
    data_wait             blocking on the input feeder inside a step
    dispatch              the jitted step-executable call
    d2h_fetch             a coalesced device->host readback
    checkpoint_snapshot   the donation-safe host copy before a save
    async_reader_drain    the off-thread metric fetch
    decode                one generate()/beam/speculative call
    serve_prefill         one serving prefill: gather + dense prefill
                          + first-token fetch (the TTFT device side)
    serve_tick            one engine tick: dispatch + d2h fetch of the
                          committed tokens (the serving hot loop)

Request-scoped serving observability (per-request lifecycles rather
than host sections) lives in serving/reqtrace.py; its JSONL records
merge into the same Perfetto view via `monitoring/collect.py --serve`.
"""

import json
import os
import socket
import sys
import threading
import time

__all__ = ["SpanTracer", "install", "uninstall", "current_tracer",
           "enabled", "span", "begin", "end", "complete", "trace_steps"]

#: Hard cap on buffered span events; beyond it new events are counted
#: as dropped instead of growing the host heap without bound (a week of
#: steps would otherwise OOM the host before anyone looked at a trace).
_DEFAULT_MAX_EVENTS = 500_000


class _NoopSpan:
    """Shared do-nothing context manager returned by `span()` when the
    tracer is disabled."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


def _process_identity():
    """This process's (index, label) for trace metadata.

    Index comes from the CLOUD_TPU_PROCESS_ID env contract first, then
    from a jax that is ALREADY imported (`sys.modules.get` — this
    module stays stdlib-only and must never pull jax in), else 0. The
    label is what Perfetto shows on the process lane.
    """
    index = 0
    value = os.environ.get("CLOUD_TPU_PROCESS_ID")
    if value is not None:
        try:
            index = int(value)
        except ValueError:
            index = 0
    else:
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                index = int(jax.process_index())
            except Exception:
                index = 0
    label = "{}/p{} (pid {})".format(
        socket.gethostname(), index, os.getpid())
    return index, label


class _Span:
    """Context manager recording one complete event on exit."""

    __slots__ = ("_tracer", "_name", "_t0")

    def __init__(self, tracer, name):
        self._tracer = tracer
        self._name = name
        self._t0 = 0

    def __enter__(self):
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, *exc):
        t0 = self._t0
        self._tracer.complete(self._name, t0,
                              time.monotonic_ns() - t0)
        return False


class SpanTracer:
    """Bounded buffer of (name, tid, t0_ns, dur_ns) span events.

    Thread-safe: spans arrive from the training thread, the async
    metric reader, and the checkpoint worker concurrently; one lock
    guards the buffer and the listener list. Listeners fire on every
    span completion (under the lock, so keep them cheap — the
    telemetry registry's histogram observe is a dict update) and feed
    the step-latency/data-wait/dispatch distributions without a second
    timing source.
    """

    def __init__(self, max_events=_DEFAULT_MAX_EVENTS):
        self._lock = threading.Lock()
        self._events = []
        self._max_events = int(max_events)
        self._dropped = 0
        self._listeners = []
        # Trace epoch: event timestamps export relative to install time
        # so the Chrome trace starts near t=0 instead of host-uptime ns.
        self._epoch_ns = time.monotonic_ns()

    def add_listener(self, fn):
        """Registers `fn(name, t0_ns, dur_ns, tid)` on span completion."""
        with self._lock:
            self._listeners.append(fn)

    def span(self, name):
        """Context manager recording one span around its body."""
        return _Span(self, name)

    def complete(self, name, t0_ns, dur_ns):
        """Records one already-measured span (begin/end style)."""
        tid = threading.get_ident()
        with self._lock:
            if len(self._events) < self._max_events:
                self._events.append((name, tid, t0_ns, dur_ns))
            else:
                self._dropped += 1
            listeners = tuple(self._listeners)
        for fn in listeners:
            try:
                fn(name, t0_ns, dur_ns, tid)
            except Exception:
                pass  # a metrics sink must never break the traced code

    def events(self):
        """Snapshot of buffered (name, tid, t0_ns, dur_ns) tuples."""
        with self._lock:
            return list(self._events)

    def dropped(self):
        """Events discarded after the buffer cap was reached."""
        with self._lock:
            return self._dropped

    def chrome_trace(self):
        """The buffered spans as a Chrome trace-event JSON object.

        Complete events ("ph":"X", microsecond ts/dur) on per-thread
        tracks; Perfetto nests them by time containment. Thread names
        ride as metadata events so tracks read "cloud-tpu-metric-
        reader" instead of a bare tid. The pid is this PROCESS's index
        (CLOUD_TPU_PROCESS_ID / jax.process_index, not a hardcoded 1),
        with process_name/process_sort_index metadata naming the lane
        "host/pN (pid OSPID)" — so per-host traces merged by the fleet
        collector land on distinct, labeled lanes instead of colliding.
        """
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
            epoch = self._epoch_ns
        names = {t.ident: t.name for t in threading.enumerate()}
        process_index, process_label = _process_identity()
        trace_events = [
            {"ph": "M", "pid": process_index, "tid": 0,
             "name": "process_name",
             "args": {"name": process_label}},
            {"ph": "M", "pid": process_index, "tid": 0,
             "name": "process_sort_index",
             "args": {"sort_index": process_index}},
        ]
        for tid in sorted({tid for _, tid, _, _ in events}):
            trace_events.append({
                "ph": "M", "pid": process_index, "tid": tid,
                "name": "thread_name",
                "args": {"name": names.get(tid, "thread-{}".format(tid))},
            })
        for name, tid, t0_ns, dur_ns in events:
            trace_events.append({
                "ph": "X", "pid": process_index, "tid": tid, "name": name,
                "ts": (t0_ns - epoch) / 1e3,
                "dur": dur_ns / 1e3,
            })
        trace = {"traceEvents": trace_events,
                 "displayTimeUnit": "ms"}
        if dropped:
            trace["metadata"] = {"dropped_events": dropped}
        return trace

    def write(self, path):
        """Writes `chrome_trace()` as JSON to `path`."""
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


# -- module seam (the None-check discipline) ----------------------------

_tracer = None


def install(tracer=None):
    """Installs `tracer` (default: a fresh SpanTracer) as the ambient
    tracer and returns it. Idempotent when one is already installed and
    no explicit tracer is given."""
    global _tracer
    if tracer is None:
        if _tracer is None:
            _tracer = SpanTracer()
    else:
        _tracer = tracer
    return _tracer


def uninstall():
    """Removes the ambient tracer (returns it, or None)."""
    global _tracer
    previous, _tracer = _tracer, None
    return previous


def current_tracer():
    return _tracer


def enabled():
    return _tracer is not None


def span(name):
    """A recording context manager when a tracer is installed, else a
    shared no-op (one global load + None check)."""
    tracer = _tracer
    if tracer is None:
        return _NOOP
    return tracer.span(name)


def begin(name):
    """Begin handle for code that cannot use `with` (loop phases).
    Returns None when disabled; pass the handle to `end()`."""
    if _tracer is None:
        return None
    return (name, time.monotonic_ns())


def end(handle):
    """Completes a `begin()` handle (no-op for None)."""
    tracer = _tracer
    if tracer is None or handle is None:
        return
    name, t0 = handle
    tracer.complete(name, t0, time.monotonic_ns() - t0)


def complete(name, t0_ns, dur_ns):
    """Records an already-measured span into the ambient tracer."""
    tracer = _tracer
    if tracer is not None:
        tracer.complete(name, t0_ns, dur_ns)


def trace_steps(iterable, step_name="train_step",
                wait_name="data_wait"):
    """Wraps a step feeder so every iteration becomes a `train_step`
    span containing a `data_wait` span.

    The generator protocol gives the exact cut points for free:
    `data_wait` covers blocking on the upstream feeder (`next(it)`),
    and the `train_step` span closes when the CONSUMER asks for the
    next item — i.e. after its dispatch + log-append body ran — so
    consecutive train_step spans tile the loop's wall time. A consumer
    `break` raises GeneratorExit at the yield; the finally completes
    the in-flight span before the generator closes.

    Callers gate on `enabled()` and pass the feeder through untouched
    when tracing is off, keeping the disabled hot loop unchanged.
    """
    tracer = _tracer
    if tracer is None:
        yield from iterable
        return
    it = iter(iterable)
    while True:
        t0 = time.monotonic_ns()
        try:
            item = next(it)
        except StopIteration:
            return
        tracer.complete(wait_name, t0, time.monotonic_ns() - t0)
        try:
            yield item
        finally:
            tracer.complete(step_name, t0, time.monotonic_ns() - t0)
