"""graftwatch collector: merge per-process telemetry into a fleet view.

Each process of a multi-host job exports its OWN graftscope artifacts
(`telemetry.jsonl` rollup lines, a Chrome `trace.json`) plus graftwatch
liveness gauges — per-process truth, but the question a fleet operator
asks is cross-host: which worker is the straggler, how far has
step-time skewed, who stopped heartbeating, which log is torn. This
CLI answers it offline, from files alone (rsync'd, gcsfuse'd, or
artifact-downloaded — no live endpoints):

    python -m cloud_tpu.monitoring.collect RUN_DIR... [--out DIR]

Inputs: directories are scanned for `telemetry.jsonl` / `*.jsonl` job
logs and `trace.json` traces (any depth); bare files work too. JSONL
records are grouped by their (host, process_index) stamp — the
utils/events identity contract — so N processes appending to N files
OR to one shared file both collate correctly, and torn trailing lines
(a crashed writer) are counted, not fatal.

Outputs under --out:
    fleet_report.json   per-process rollups + fleet verdict (skew,
                        straggler, liveness, corrupt-line census)
    trace.json          one merged Chrome trace: every process on its
                        own labeled pid lane (Perfetto-ready)
    fleet.prom          Prometheus textfile with {host=,process=}
                        labels per series, plus fleet-level gauges
"""

import argparse
import json
import logging
import os
import sys

logger = logging.getLogger("cloud_tpu")

__all__ = ["discover_inputs", "load_process_records", "merge_traces",
           "fleet_report", "render_fleet_prometheus", "collect", "main"]

STEP_HISTOGRAM = "cloud_tpu_step_latency_seconds"
STEPS_PER_SEC = "cloud_tpu_steps_per_sec"

_WATCH_GAUGES = (
    "cloud_tpu_watch_alive",
    "cloud_tpu_watch_heartbeat_age_seconds",
    "cloud_tpu_watch_last_step_age_seconds",
    "cloud_tpu_watch_last_step",
)


def discover_inputs(paths):
    """Expands files/directories -> (jsonl_paths, trace_paths).

    Directories are walked; `*.jsonl` files are telemetry/job logs,
    `trace.json` (and `trace*.json`) files are Chrome traces. Bare
    file arguments are classified the same way. Order is stable
    (sorted within each directory) so lane assignment is
    deterministic.
    """
    jsonl_paths, trace_paths = [], []
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs.sort()
                for name in sorted(files):
                    full = os.path.join(root, name)
                    if name.endswith(".jsonl"):
                        jsonl_paths.append(full)
                    elif (name == "trace.json"
                          or (name.startswith("trace")
                              and name.endswith(".json"))):
                        trace_paths.append(full)
        elif path.endswith(".jsonl"):
            jsonl_paths.append(path)
        elif path.endswith(".json"):
            trace_paths.append(path)
        else:
            logger.warning("collect: skipping unrecognized input %s",
                           path)
    return jsonl_paths, trace_paths


def _process_key(record):
    """(host, process_index) identity of a JSONL record. Pre-PR-7
    records carry no process stamp; they collapse onto index 0 of
    their host (or "unknown") rather than being dropped."""
    return (str(record.get("host", "unknown")),
            int(record.get("process_index", 0) or 0))


def load_process_records(jsonl_paths):
    """Reads every JSONL input -> ({(host, index): [records]},
    {path: corrupt_line_count}).

    Records are grouped by writer identity, NOT by file: a shared log
    with interleaved appenders and one-file-per-process layouts both
    land in the same shape. Unreadable files are reported in the
    corrupt census (count -1) instead of aborting the merge.
    """
    from cloud_tpu.utils import events

    by_process = {}
    corrupt = {}
    for path in jsonl_paths:
        try:
            records, stats = events.read_job_events(path,
                                                    with_stats=True)
        except Exception as e:
            logger.warning("collect: unreadable input %s (%s)", path, e)
            corrupt[path] = -1
            continue
        if stats["corrupt_lines"]:
            corrupt[path] = stats["corrupt_lines"]
        for record in records:
            by_process.setdefault(_process_key(record),
                                  []).append(record)
    return by_process, corrupt


def _last_telemetry(records):
    """The newest "telemetry" rollup in a record list (each flush line
    supersedes the previous one — snapshots are cumulative)."""
    last = None
    for record in records:
        if record.get("kind") == "telemetry":
            last = record
    return last


def _process_rollup(key, records):
    host, index = key
    rollup = {
        "host": host,
        "process_index": index,
        "events": len(records),
        "event_kinds": sorted({str(r.get("kind")) for r in records}),
    }
    stalls = [r for r in records
              if r.get("kind") == "graftwatch"
              and isinstance(r.get("payload"), dict)
              and r["payload"].get("event") == "stall"]
    if stalls:
        rollup["stalls"] = len(stalls)
        rollup["last_stall"] = stalls[-1]["payload"]
    telemetry = _last_telemetry(records)
    if telemetry is None:
        return rollup
    payload = telemetry.get("payload") or {}
    gauges = payload.get("gauges") or {}
    counters = payload.get("counters") or {}
    histograms = payload.get("histograms") or {}
    step = histograms.get(STEP_HISTOGRAM) or {}
    rollup["steps_per_sec"] = gauges.get(STEPS_PER_SEC)
    rollup["step_latency"] = {
        "count": step.get("count", 0),
        "p50": step.get("p50"),
        "p95": step.get("p95"),
        "p99": step.get("p99"),
    }
    rollup["steps_total"] = counters.get("cloud_tpu_training_steps_total")
    rollup["compiles_total"] = counters.get("cloud_tpu_compiles_total")
    watch = {name: gauges[name] for name in _WATCH_GAUGES
             if name in gauges}
    if watch:
        rollup["watch"] = watch
    return rollup


def fleet_report(by_process, corrupt=None):
    """Per-process rollups + the fleet verdict.

    Skew is (max p50 − min p50) / min p50 over processes that reported
    a step-latency histogram; the straggler is the max-p50 process
    (falling back to min steps/sec when no latencies exist). A process
    whose watch gauges report alive=0 — or that logged a graftwatch
    stall event — is listed dead regardless of its throughput numbers.
    """
    processes = {}
    for key in sorted(by_process):
        rollup = _process_rollup(key, by_process[key])
        processes["{}/p{}".format(*key)] = rollup

    with_p50 = {name: r["step_latency"]["p50"]
                for name, r in processes.items()
                if r.get("step_latency", {}).get("p50")}
    fleet = {"process_count": len(processes)}
    if with_p50:
        slowest = max(with_p50, key=with_p50.get)
        fastest = min(with_p50, key=with_p50.get)
        low, high = with_p50[fastest], with_p50[slowest]
        fleet["step_p50_min_seconds"] = low
        fleet["step_p50_max_seconds"] = high
        fleet["step_p50_skew_pct"] = (100.0 * (high - low) / low
                                      if low > 0 else 0.0)
        fleet["straggler"] = slowest
        fleet["fastest"] = fastest
    else:
        with_rate = {name: r["steps_per_sec"]
                     for name, r in processes.items()
                     if r.get("steps_per_sec")}
        if with_rate:
            fleet["straggler"] = min(with_rate, key=with_rate.get)
    dead = sorted(
        name for name, r in processes.items()
        if r.get("stalls")
        or (r.get("watch", {}).get("cloud_tpu_watch_alive") == 0.0))
    if dead:
        fleet["dead"] = dead
    report = {"format": "cloud_tpu.fleet_report.v1",
              "processes": processes, "fleet": fleet}
    if corrupt:
        report["corrupt_inputs"] = dict(corrupt)
    return report


def _trace_label(trace, fallback):
    """The process label an input trace declared for itself (the
    spans.py process_name metadata), else `fallback`."""
    for event in trace.get("traceEvents", ()):
        if event.get("ph") == "M" and event.get("name") == "process_name":
            name = (event.get("args") or {}).get("name")
            if name:
                return str(name)
    return fallback


def merge_traces(trace_paths):
    """Merges per-process Chrome traces into one multi-lane trace.

    Every input is re-stamped onto its own pid lane (dense ints in
    input order) — two hosts that both exported process_index 0 must
    not collide — old process metadata is dropped, and fresh
    process_name/process_sort_index metadata labels each lane with the
    name the input declared for itself. Unparseable inputs are skipped
    with a warning (one corrupt rsync'd file must not kill the fleet
    view).
    """
    merged = []
    lanes = []
    lane = 0
    for path in trace_paths:
        try:
            with open(path) as f:
                trace = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("collect: unreadable trace %s (%s)", path, e)
            continue
        label = _trace_label(
            trace, os.path.basename(os.path.dirname(path)) or path)
        lanes.append({"pid": lane, "label": label, "path": path})
        merged.append({"ph": "M", "pid": lane, "tid": 0,
                       "name": "process_name",
                       "args": {"name": label}})
        merged.append({"ph": "M", "pid": lane, "tid": 0,
                       "name": "process_sort_index",
                       "args": {"sort_index": lane}})
        for event in trace.get("traceEvents", ()):
            if (event.get("ph") == "M"
                    and event.get("name") in ("process_name",
                                              "process_sort_index")):
                continue
            event = dict(event)
            event["pid"] = lane
            merged.append(event)
        lane += 1
    return {"traceEvents": merged, "displayTimeUnit": "ms",
            "metadata": {"lanes": lanes}}, lanes


def _prom_number(value):
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    return repr(float(value))


def render_fleet_prometheus(report):
    """The fleet report as Prometheus textfile lines with
    {host=,process=} labels per series (the single-registry renderer
    in export.py has no label support — fleet exposition hand-writes
    them) plus fleet-level summary gauges."""
    lines = []

    def emit(name, labels, value):
        if value is None:
            return
        if labels:
            body = ",".join('{}="{}"'.format(k, v)
                            for k, v in labels.items())
            lines.append("{}{{{}}} {}".format(name, body,
                                              _prom_number(value)))
        else:
            lines.append("{} {}".format(name, _prom_number(value)))

    for name in sorted(report["processes"]):
        rollup = report["processes"][name]
        labels = {"host": rollup["host"],
                  "process": str(rollup["process_index"])}
        emit("cloud_tpu_fleet_steps_per_sec", labels,
             rollup.get("steps_per_sec"))
        step = rollup.get("step_latency") or {}
        for quantile in ("p50", "p95", "p99"):
            emit("cloud_tpu_fleet_step_latency_seconds_" + quantile,
                 labels, step.get(quantile))
        for gauge in _WATCH_GAUGES:
            emit("cloud_tpu_fleet_" + gauge[len("cloud_tpu_"):],
                 labels, rollup.get("watch", {}).get(gauge))
        emit("cloud_tpu_fleet_stalls_total", labels,
             rollup.get("stalls", 0))
    fleet = report["fleet"]
    emit("cloud_tpu_fleet_process_count", None, fleet["process_count"])
    emit("cloud_tpu_fleet_step_p50_skew_pct", None,
         fleet.get("step_p50_skew_pct"))
    emit("cloud_tpu_fleet_dead_processes", None,
         len(fleet.get("dead", ())))
    corrupt = report.get("corrupt_inputs") or {}
    emit("cloud_tpu_fleet_corrupt_inputs", None, len(corrupt))
    return "\n".join(lines) + "\n"


def collect(inputs, out_dir):
    """The full pass: discover -> group -> report -> merge -> write.
    Returns the fleet report dict (with an extra "outputs" section
    naming what was written)."""
    jsonl_paths, trace_paths = discover_inputs(inputs)
    by_process, corrupt = load_process_records(jsonl_paths)
    report = fleet_report(by_process, corrupt)
    os.makedirs(out_dir, exist_ok=True)
    outputs = {}

    report_path = os.path.join(out_dir, "fleet_report.json")
    with open(report_path, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    outputs["report"] = report_path

    if trace_paths:
        trace, lanes = merge_traces(trace_paths)
        trace_path = os.path.join(out_dir, "trace.json")
        with open(trace_path, "w") as f:
            json.dump(trace, f)
        outputs["trace"] = trace_path
        outputs["lanes"] = len(lanes)

    prom_path = os.path.join(out_dir, "fleet.prom")
    with open(prom_path, "w") as f:
        f.write(render_fleet_prometheus(report))
    outputs["prom"] = prom_path

    report["outputs"] = outputs
    return report


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m cloud_tpu.monitoring.collect",
        description="Merge per-process cloud_tpu telemetry into one "
                    "fleet report + multi-lane trace.")
    parser.add_argument("inputs", nargs="+",
                        help="telemetry directories, *.jsonl logs, or "
                             "trace.json files")
    parser.add_argument("--out", default="fleet",
                        help="output directory (default ./fleet)")
    args = parser.parse_args(argv)
    report = collect(args.inputs, args.out)
    fleet = report["fleet"]
    print("fleet: {} process(es)".format(fleet["process_count"]))
    if "step_p50_skew_pct" in fleet:
        print("step p50 skew: {:.1f}% (straggler: {})".format(
            fleet["step_p50_skew_pct"], fleet["straggler"]))
    for name in fleet.get("dead", ()):
        print("DEAD: {}".format(name))
    for path, count in sorted(
            (report.get("corrupt_inputs") or {}).items()):
        print("torn input: {} ({} corrupt line(s))".format(
            path, "unreadable" if count < 0 else count))
    for key in ("report", "trace", "prom"):
        if key in report["outputs"]:
            print("wrote {}".format(report["outputs"][key]))
    return 0 if fleet["process_count"] else 1


if __name__ == "__main__":
    sys.exit(main())
