"""graftwatch collector: merge per-process telemetry into a fleet view.

Each process of a multi-host job exports its OWN graftscope artifacts
(`telemetry.jsonl` rollup lines, a Chrome `trace.json`) plus graftwatch
liveness gauges — per-process truth, but the question a fleet operator
asks is cross-host: which worker is the straggler, how far has
step-time skewed, who stopped heartbeating, which log is torn. This
CLI answers it offline, from files alone (rsync'd, gcsfuse'd, or
artifact-downloaded — no live endpoints):

    python -m cloud_tpu.monitoring.collect RUN_DIR... [--out DIR]

Inputs: directories are scanned for `telemetry.jsonl` / `*.jsonl` job
logs and `trace.json` traces (any depth); bare files work too. JSONL
records are grouped by their (host, process_index) stamp — the
utils/events identity contract — so N processes appending to N files
OR to one shared file both collate correctly, and torn trailing lines
(a crashed writer) are counted, not fatal.

Outputs under --out:
    fleet_report.json   per-process rollups + fleet verdict (skew,
                        straggler, liveness, corrupt-line census)
    trace.json          one merged Chrome trace: every process on its
                        own labeled pid lane (Perfetto-ready)
    fleet.prom          Prometheus textfile with {host=,process=}
                        labels per series, plus fleet-level gauges

Serve mode (graftlens): `--serve` additionally rolls `reqtrace` JSONL
records (serving/reqtrace.py lifecycles, grouped per (host, pid, rid))
into:
    serve_report.json   per-request latency decomposition -> TTFT/TPOT
                        percentiles split by prefix-cache hit/miss and
                        prompt bucket, queue/reserve wait breakdown,
                        slot-occupancy timeline, goodput against
                        `--slo-ttft` / `--slo-tpot`, and the graftpack
                        kv_tier split: follow-up TTFT classed promoted
                        (host pages copied back) vs device_hit vs
                        re_prefill, plus pages demoted/promoted
    trace.json          grows a "graftserve requests" lane: one tid per
                        request, phases tiled submit->complete as "X"
                        events (the per-request waterfall, Perfetto-
                        ready next to the span lanes)
"""

import argparse
import json
import logging
import os
import sys

logger = logging.getLogger("cloud_tpu")

__all__ = ["discover_inputs", "load_process_records", "merge_traces",
           "fleet_report", "render_fleet_prometheus", "collect", "main",
           "request_lifecycles", "serve_report", "serve_trace_lane"]

STEP_HISTOGRAM = "cloud_tpu_step_latency_seconds"
STEPS_PER_SEC = "cloud_tpu_steps_per_sec"

_WATCH_GAUGES = (
    "cloud_tpu_watch_alive",
    "cloud_tpu_watch_heartbeat_age_seconds",
    "cloud_tpu_watch_last_step_age_seconds",
    "cloud_tpu_watch_last_step",
)


def discover_inputs(paths):
    """Expands files/directories -> (jsonl_paths, trace_paths).

    Directories are walked; `*.jsonl` files are telemetry/job logs,
    `trace.json` (and `trace*.json`) files are Chrome traces. Bare
    file arguments are classified the same way. Order is stable
    (sorted within each directory) so lane assignment is
    deterministic.
    """
    jsonl_paths, trace_paths = [], []
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs.sort()
                for name in sorted(files):
                    full = os.path.join(root, name)
                    if name.endswith(".jsonl"):
                        jsonl_paths.append(full)
                    elif (name == "trace.json"
                          or (name.startswith("trace")
                              and name.endswith(".json"))):
                        trace_paths.append(full)
        elif path.endswith(".jsonl"):
            jsonl_paths.append(path)
        elif path.endswith(".json"):
            trace_paths.append(path)
        else:
            logger.warning("collect: skipping unrecognized input %s",
                           path)
    return jsonl_paths, trace_paths


def _process_key(record):
    """(host, process_index) identity of a JSONL record. Pre-PR-7
    records carry no process stamp; they collapse onto index 0 of
    their host (or "unknown") rather than being dropped."""
    return (str(record.get("host", "unknown")),
            int(record.get("process_index", 0) or 0))


def load_process_records(jsonl_paths):
    """Reads every JSONL input -> ({(host, index): [records]},
    {path: corrupt_line_count}).

    Records are grouped by writer identity, NOT by file: a shared log
    with interleaved appenders and one-file-per-process layouts both
    land in the same shape. Unreadable files are reported in the
    corrupt census (count -1) instead of aborting the merge.
    """
    from cloud_tpu.utils import events

    by_process = {}
    corrupt = {}
    for path in jsonl_paths:
        try:
            records, stats = events.read_job_events(path,
                                                    with_stats=True)
        except Exception as e:
            logger.warning("collect: unreadable input %s (%s)", path, e)
            corrupt[path] = -1
            continue
        if stats["corrupt_lines"]:
            corrupt[path] = stats["corrupt_lines"]
        for record in records:
            by_process.setdefault(_process_key(record),
                                  []).append(record)
    return by_process, corrupt


def _last_telemetry(records):
    """The newest "telemetry" rollup in a record list (each flush line
    supersedes the previous one — snapshots are cumulative)."""
    last = None
    for record in records:
        if record.get("kind") == "telemetry":
            last = record
    return last


def _process_rollup(key, records):
    host, index = key
    rollup = {
        "host": host,
        "process_index": index,
        "events": len(records),
        "event_kinds": sorted({str(r.get("kind")) for r in records}),
    }
    stalls = [r for r in records
              if r.get("kind") == "graftwatch"
              and isinstance(r.get("payload"), dict)
              and r["payload"].get("event") == "stall"]
    if stalls:
        rollup["stalls"] = len(stalls)
        rollup["last_stall"] = stalls[-1]["payload"]
    telemetry = _last_telemetry(records)
    if telemetry is None:
        return rollup
    payload = telemetry.get("payload") or {}
    gauges = payload.get("gauges") or {}
    counters = payload.get("counters") or {}
    histograms = payload.get("histograms") or {}
    step = histograms.get(STEP_HISTOGRAM) or {}
    rollup["steps_per_sec"] = gauges.get(STEPS_PER_SEC)
    rollup["step_latency"] = {
        "count": step.get("count", 0),
        "p50": step.get("p50"),
        "p95": step.get("p95"),
        "p99": step.get("p99"),
    }
    rollup["steps_total"] = counters.get("cloud_tpu_training_steps_total")
    rollup["compiles_total"] = counters.get("cloud_tpu_compiles_total")
    watch = {name: gauges[name] for name in _WATCH_GAUGES
             if name in gauges}
    if watch:
        rollup["watch"] = watch
    return rollup


def fleet_report(by_process, corrupt=None):
    """Per-process rollups + the fleet verdict.

    Skew is (max p50 − min p50) / min p50 over processes that reported
    a step-latency histogram; the straggler is the max-p50 process
    (falling back to min steps/sec when no latencies exist). A process
    whose watch gauges report alive=0 — or that logged a graftwatch
    stall event — is listed dead regardless of its throughput numbers.
    """
    processes = {}
    for key in sorted(by_process):
        rollup = _process_rollup(key, by_process[key])
        processes["{}/p{}".format(*key)] = rollup

    with_p50 = {name: r["step_latency"]["p50"]
                for name, r in processes.items()
                if r.get("step_latency", {}).get("p50")}
    fleet = {"process_count": len(processes)}
    if with_p50:
        slowest = max(with_p50, key=with_p50.get)
        fastest = min(with_p50, key=with_p50.get)
        low, high = with_p50[fastest], with_p50[slowest]
        fleet["step_p50_min_seconds"] = low
        fleet["step_p50_max_seconds"] = high
        fleet["step_p50_skew_pct"] = (100.0 * (high - low) / low
                                      if low > 0 else 0.0)
        fleet["straggler"] = slowest
        fleet["fastest"] = fastest
    else:
        with_rate = {name: r["steps_per_sec"]
                     for name, r in processes.items()
                     if r.get("steps_per_sec")}
        if with_rate:
            fleet["straggler"] = min(with_rate, key=with_rate.get)
    dead = sorted(
        name for name, r in processes.items()
        if r.get("stalls")
        or (r.get("watch", {}).get("cloud_tpu_watch_alive") == 0.0))
    if dead:
        fleet["dead"] = dead
    report = {"format": "cloud_tpu.fleet_report.v1",
              "processes": processes, "fleet": fleet}
    if corrupt:
        report["corrupt_inputs"] = dict(corrupt)
    return report


def _trace_label(trace, fallback):
    """The process label an input trace declared for itself (the
    spans.py process_name metadata), else `fallback`."""
    for event in trace.get("traceEvents", ()):
        if event.get("ph") == "M" and event.get("name") == "process_name":
            name = (event.get("args") or {}).get("name")
            if name:
                return str(name)
    return fallback


def merge_traces(trace_paths):
    """Merges per-process Chrome traces into one multi-lane trace.

    Every input is re-stamped onto its own pid lane (dense ints in
    input order) — two hosts that both exported process_index 0 must
    not collide — old process metadata is dropped, and fresh
    process_name/process_sort_index metadata labels each lane with the
    name the input declared for itself. Unparseable inputs are skipped
    with a warning (one corrupt rsync'd file must not kill the fleet
    view).
    """
    merged = []
    lanes = []
    lane = 0
    for path in trace_paths:
        try:
            with open(path) as f:
                trace = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("collect: unreadable trace %s (%s)", path, e)
            continue
        label = _trace_label(
            trace, os.path.basename(os.path.dirname(path)) or path)
        lanes.append({"pid": lane, "label": label, "path": path})
        merged.append({"ph": "M", "pid": lane, "tid": 0,
                       "name": "process_name",
                       "args": {"name": label}})
        merged.append({"ph": "M", "pid": lane, "tid": 0,
                       "name": "process_sort_index",
                       "args": {"sort_index": lane}})
        for event in trace.get("traceEvents", ()):
            if (event.get("ph") == "M"
                    and event.get("name") in ("process_name",
                                              "process_sort_index")):
                continue
            event = dict(event)
            event["pid"] = lane
            merged.append(event)
        lane += 1
    return {"traceEvents": merged, "displayTimeUnit": "ms",
            "metadata": {"lanes": lanes}}, lanes


def _prom_number(value):
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    return repr(float(value))


def render_fleet_prometheus(report):
    """The fleet report as Prometheus textfile lines with
    {host=,process=} labels per series (the single-registry renderer
    in export.py has no label support — fleet exposition hand-writes
    them) plus fleet-level summary gauges."""
    lines = []

    def emit(name, labels, value):
        if value is None:
            return
        if labels:
            body = ",".join('{}="{}"'.format(k, v)
                            for k, v in labels.items())
            lines.append("{}{{{}}} {}".format(name, body,
                                              _prom_number(value)))
        else:
            lines.append("{} {}".format(name, _prom_number(value)))

    for name in sorted(report["processes"]):
        rollup = report["processes"][name]
        labels = {"host": rollup["host"],
                  "process": str(rollup["process_index"])}
        emit("cloud_tpu_fleet_steps_per_sec", labels,
             rollup.get("steps_per_sec"))
        step = rollup.get("step_latency") or {}
        for quantile in ("p50", "p95", "p99"):
            emit("cloud_tpu_fleet_step_latency_seconds_" + quantile,
                 labels, step.get(quantile))
        for gauge in _WATCH_GAUGES:
            emit("cloud_tpu_fleet_" + gauge[len("cloud_tpu_"):],
                 labels, rollup.get("watch", {}).get(gauge))
        emit("cloud_tpu_fleet_stalls_total", labels,
             rollup.get("stalls", 0))
    fleet = report["fleet"]
    emit("cloud_tpu_fleet_process_count", None, fleet["process_count"])
    emit("cloud_tpu_fleet_step_p50_skew_pct", None,
         fleet.get("step_p50_skew_pct"))
    emit("cloud_tpu_fleet_dead_processes", None,
         len(fleet.get("dead", ())))
    corrupt = report.get("corrupt_inputs") or {}
    emit("cloud_tpu_fleet_corrupt_inputs", None, len(corrupt))
    return "\n".join(lines) + "\n"


# -- graftlens serve mode ---------------------------------------------

#: Lifecycle boundary events in pipeline order; the time between two
#: consecutive PRESENT boundaries is attributed to the phase named
#: after the later one. The tiling telescopes: phase sums equal the
#: submitted->complete span exactly, so the waterfall accounts for the
#: request's end-to-end latency (the accounting_residual check).
_BOUNDARIES = ("submitted", "queued", "pages_reserved", "prefill",
               "slot_insert", "complete")
_PHASE_OF = {
    "queued": "queue_wait",
    "pages_reserved": "admit",
    "prefill": "prefill",
    "slot_insert": "await_slot",
    "complete": "decode",
}


def _quantile(values, q):
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return None
    pos = (len(vals) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(vals) - 1)
    return vals[lo] + (vals[hi] - vals[lo]) * (pos - lo)


def _pcts(values):
    vals = [v for v in values if v is not None]
    out = {"count": len(vals)}
    for name, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
        out[name] = _quantile(vals, q)
    out["mean"] = sum(vals) / len(vals) if vals else None
    return out


def request_lifecycles(by_process):
    """reqtrace records -> ({"host/pid/rid": [events]}, [global events]).

    Event dicts are the record payloads plus "_monotonic"/"_time"
    stamps, sorted by emit time per request. rid-less payloads
    (prefix_evict) land in the global list. rids are unique per
    process, so the (host, pid) prefix keeps two processes' r000000
    apart in one merged view.
    """
    lifecycles = {}
    globals_ = []
    for records in by_process.values():
        for record in records:
            if record.get("kind") != "reqtrace":
                continue
            payload = record.get("payload")
            if not isinstance(payload, dict) or "event" not in payload:
                continue
            event = dict(payload)
            event["_monotonic"] = float(record.get("monotonic", 0.0))
            event["_time"] = record.get("time")
            rid = payload.get("rid")
            if rid is None:
                globals_.append(event)
                continue
            key = "{}/{}/{}".format(record.get("host", "unknown"),
                                    record.get("pid", 0), rid)
            lifecycles.setdefault(key, []).append(event)
    for events in lifecycles.values():
        events.sort(key=lambda e: e["_monotonic"])
    globals_.sort(key=lambda e: e["_monotonic"])
    return lifecycles, globals_


def _summarize_request(events):
    """One lifecycle -> summary row: identity fields, terminal status,
    per-phase durations (boundary tiling), and latency cross-checks."""
    first = {}
    for event in events:
        first.setdefault(event["event"], event)
    summary = {"events": len(events)}
    submitted = first.get("submitted")
    if submitted is not None:
        summary["prompt_len"] = submitted.get("prompt_len")
        summary["max_new"] = submitted.get("max_new")
    complete = first.get("complete")
    fail = first.get("fail")
    shed = first.get("shed")
    summary["terminal"] = ("complete" if complete is not None
                           else "fail" if fail is not None
                           else "shed" if shed is not None else None)
    prefill = first.get("prefill")
    probe = first.get("radix_probe")
    prefix_len = None
    if complete is not None:
        prefix_len = complete.get("prefix_len")
    elif prefill is not None:
        prefix_len = prefill.get("prefix_len")
    summary["prefix_len"] = prefix_len
    if prefix_len is not None:
        summary["hit"] = bool(prefix_len)
    elif probe is not None:
        summary["hit"] = bool(probe.get("hit"))
    else:
        summary["hit"] = None
    if prefill is not None:
        summary["bucket"] = prefill.get("bucket")
        summary["prefill_dur_s"] = prefill.get("dur_s")
    queued = first.get("queued")
    if queued is not None:
        summary["queue_wait_s"] = queued.get("wait_s")
    reserved = first.get("pages_reserved")
    if reserved is not None:
        summary["reserve_wait_s"] = reserved.get("wait_s")
        summary["pages"] = reserved.get("pages")
    if complete is not None:
        summary["ttft_s"] = complete.get("ttft_s")
        summary["latency_s"] = complete.get("latency_s")
        tokens = complete.get("tokens")
        summary["tokens"] = tokens
        if (tokens and tokens > 1
                and summary.get("ttft_s") is not None
                and summary.get("latency_s") is not None):
            summary["tpot_s"] = ((summary["latency_s"]
                                  - summary["ttft_s"]) / (tokens - 1))
    if fail is not None:
        summary["error"] = fail.get("error")
    if shed is not None:
        summary["shed_reason"] = shed.get("reason")
        summary["predicted_ttft_s"] = shed.get("predicted_ttft")
    # graftstorm chaos census: a requeued rid emits slot_fault/requeue
    # mid-lifecycle and then terminates normally — never an orphan.
    faults = {}
    for event in events:
        if event["event"] == "slot_fault":
            kind = event.get("kind") or "unknown"
            faults[kind] = faults.get(kind, 0) + 1
    requeues = sum(1 for e in events if e["event"] == "requeue")
    if faults:
        summary["faults"] = faults
    if requeues:
        summary["requeues"] = requeues
    summary["chaos"] = bool(faults or requeues)
    # Chunked prefill: per-chunk dispatch events tile INSIDE the
    # (pages_reserved, prefill] phase — they are sub-phase detail, not
    # lifecycle boundaries, so the boundary tiling (and its telescoping
    # residual check) is untouched by their presence.
    chunk_events = [e for e in events if e["event"] == "prefill_chunk"]
    if chunk_events:
        summary["prefill_chunks"] = max(
            int(e.get("n", 0)) for e in chunk_events)
        summary["prefill_chunk_dispatches"] = len(chunk_events)
        summary["prefill_chunk_tokens"] = sum(
            int(e.get("tokens", 0)) for e in chunk_events)
    summary["chunked"] = bool(chunk_events)
    # graftpack page-tier movement: a promote INSIDE admission marks
    # the request's TTFT class (promoted vs device-cache-hit vs
    # re-prefill); a demote at completion is census only.
    promotes = [e for e in events if e["event"] == "page_promote"]
    demotes = [e for e in events if e["event"] == "page_demote"]
    summary["promoted"] = bool(promotes)
    if promotes:
        summary["promoted_pages"] = sum(
            int(e.get("pages", 0)) for e in promotes)
    if demotes:
        summary["demoted_pages"] = sum(
            int(e.get("pages", 0)) for e in demotes)
    present = [(name, first[name]["_monotonic"])
               for name in _BOUNDARIES if name in first]
    phases = {}
    for (_, t_a), (name_b, t_b) in zip(present, present[1:]):
        phase = _PHASE_OF[name_b]
        phases[phase] = phases.get(phase, 0.0) + max(t_b - t_a, 0.0)
    summary["phases_s"] = phases
    if complete is not None and submitted is not None:
        span = complete["_monotonic"] - submitted["_monotonic"]
        summary["trace_span_s"] = span
        if summary.get("latency_s") is not None:
            # latency is measured at future-resolution; the traced span
            # tiles submitted->complete. |residual| beyond a few ms
            # means an emission site stopped tiling.
            summary["accounting_residual_s"] = (summary["latency_s"]
                                                - span)
    return summary


def serve_report(lifecycles, globals_=(), slo_ttft=None, slo_tpot=None):
    """Per-request lifecycles -> the serve report dict.

    Goodput = completed AND ttft <= slo_ttft AND tpot <= slo_tpot,
    over ALL submitted requests (sheds/failures/orphans count against
    it). A None SLO target passes that axis; single-token requests
    have no TPOT and pass the TPOT axis. The hit/miss goodput split
    uses completed requests of that class as its denominator (an
    orphan has no authoritative class).
    """
    requests = {key: _summarize_request(events)
                for key, events in lifecycles.items()}
    rows = list(requests.values())
    completed = [r for r in rows if r["terminal"] == "complete"]
    failed = [r for r in rows if r["terminal"] == "fail"]
    shed_rows = [r for r in rows if r["terminal"] == "shed"]
    orphans = sorted(key for key, r in requests.items()
                     if r["terminal"] is None)

    def _good(row):
        if row["terminal"] != "complete":
            return False
        if slo_ttft is not None and (row.get("ttft_s") is None
                                     or row["ttft_s"] > slo_ttft):
            return False
        tpot = row.get("tpot_s")
        if slo_tpot is not None and tpot is not None and tpot > slo_tpot:
            return False
        return True

    def _goodput(rows_subset, denominator):
        if not denominator:
            return None
        return sum(1 for r in rows_subset if _good(r)) / denominator

    hits = [r for r in completed if r.get("hit")]
    misses = [r for r in completed if r.get("hit") is False]
    by_bucket = {}
    for row in completed:
        bucket = row.get("bucket")
        if bucket is not None:
            by_bucket.setdefault(int(bucket), []).append(row)

    occupancy = sorted(
        (event["_monotonic"], event.get("active_slots"))
        for events in lifecycles.values() for event in events
        if event["event"] == "tick_commit"
        and event.get("active_slots") is not None)
    timeline = []
    if occupancy:
        t0 = occupancy[0][0]
        stride = max(1, len(occupancy) // 240)
        timeline = [[round(t - t0, 6), slots]
                    for t, slots in occupancy[::stride]]
    residuals = [abs(r["accounting_residual_s"]) for r in completed
                 if r.get("accounting_residual_s") is not None]
    phase_names = sorted({name for r in rows
                          for name in r.get("phases_s", ())})
    report = {
        "format": "cloud_tpu.serve_report.v1",
        "slo": {"ttft_s": slo_ttft, "tpot_s": slo_tpot},
        "requests": {
            "submitted": len(rows),
            "completed": len(completed),
            "failed": len(failed),
            "shed": len(shed_rows),
            "orphaned": len(orphans),
            "orphans": orphans,
        },
        "goodput": {
            "overall": _goodput(completed, len(rows)) or 0.0,
            "hit": _goodput(hits, len(hits)),
            "miss": _goodput(misses, len(misses)),
        },
        "ttft": {
            "overall": _pcts([r.get("ttft_s") for r in completed]),
            "hit": _pcts([r.get("ttft_s") for r in hits]),
            "miss": _pcts([r.get("ttft_s") for r in misses]),
            "by_bucket": {
                str(bucket): _pcts([r.get("ttft_s") for r in rows_b])
                for bucket, rows_b in sorted(by_bucket.items())},
        },
        "tpot": {
            "overall": _pcts([r.get("tpot_s") for r in completed]),
            "hit": _pcts([r.get("tpot_s") for r in hits]),
            "miss": _pcts([r.get("tpot_s") for r in misses]),
        },
        "latency": _pcts([r.get("latency_s") for r in completed]),
        "queue_wait": _pcts([r.get("queue_wait_s") for r in rows]),
        "reserve_wait": _pcts([r.get("reserve_wait_s") for r in rows]),
        "phases": {name: _pcts([r.get("phases_s", {}).get(name)
                                for r in rows])
                   for name in phase_names},
        "accounting_max_residual_s": max(residuals) if residuals
        else None,
        "slot_occupancy": {
            "mean": (sum(s for _, s in occupancy) / len(occupancy)
                     if occupancy else None),
            "max": max((s for _, s in occupancy), default=None),
            "timeline": timeline,
        },
        "prefix_evict_pages": sum(e.get("pages", 0) for e in globals_
                                  if e["event"] == "prefix_evict"),
        "per_request": requests,
    }
    # graftpack KV-tier split: completed requests classed by how their
    # prefix was served — promoted (host tier copied pages back),
    # device_hit (trie pages resident, no promote), re_prefill (no
    # prefix at all). The promoted-vs-device_hit TTFT gap is the cost
    # of the H2D copies; promoted-vs-re_prefill is the win.
    promoted = [r for r in completed if r.get("promoted")]
    device_hit = [r for r in completed
                  if not r.get("promoted") and r.get("hit")]
    re_prefill = [r for r in completed
                  if not r.get("promoted") and r.get("hit") is False]
    report["kv_tier"] = {
        "promoted_requests": len(promoted),
        "device_hit_requests": len(device_hit),
        "re_prefill_requests": len(re_prefill),
        "pages_promoted": sum(r.get("promoted_pages", 0)
                              for r in rows),
        "pages_demoted": sum(r.get("demoted_pages", 0) for r in rows),
        "ttft": {
            "promoted": _pcts([r.get("ttft_s") for r in promoted]),
            "device_hit": _pcts([r.get("ttft_s")
                                 for r in device_hit]),
            "re_prefill": _pcts([r.get("ttft_s")
                                 for r in re_prefill]),
        },
    }
    # Chunked-prefill census: who prefilled in chunks, how many, and
    # the prefill-phase cost per class — the A/B surface for the
    # interleave (chunked prefills SHOULD cost more wall time
    # end-to-end; the win shows up in decode_by_prompt_len below).
    chunked_rows = [r for r in completed if r.get("chunked")]
    unchunked_rows = [r for r in completed if not r.get("chunked")]
    report["prefill_chunks"] = {
        "chunked_requests": len(chunked_rows),
        "unchunked_requests": len(unchunked_rows),
        "chunk_dispatches": sum(
            r.get("prefill_chunk_dispatches", 0) for r in rows),
        "chunks_per_request": _pcts(
            [r.get("prefill_chunks") for r in chunked_rows]),
        "prefill_dur": {
            "chunked": _pcts(
                [r.get("prefill_dur_s") for r in chunked_rows]),
            "unchunked": _pcts(
                [r.get("prefill_dur_s") for r in unchunked_rows]),
        },
    }
    # Decode p99 vs prompt length: per-request TPOT percentiles in
    # pow2 prompt buckets. Without chunking, SHORT-prompt requests
    # resident while a long prompt prefills eat the stall — their
    # bucket's p99 blows up; with chunking every bucket stays near the
    # tick time. This section is where that shows.
    by_prompt = {}
    for row in completed:
        plen = row.get("prompt_len")
        if not plen or row.get("tpot_s") is None:
            continue
        bucket = 1
        while bucket < plen:
            bucket *= 2
        by_prompt.setdefault(bucket, []).append(row["tpot_s"])
    report["decode_by_prompt_len"] = {
        str(bucket): _pcts(vals)
        for bucket, vals in sorted(by_prompt.items())}
    # graftstorm: fault/requeue/shed census + goodput-under-chaos. A
    # chaos row saw >= 1 slot_fault or requeue; its goodput shows the
    # recovery-path tax relative to untouched (clean) requests.
    chaos_rows = [r for r in rows if r.get("chaos")]
    clean_rows = [r for r in rows if not r.get("chaos")]
    fault_census = {}
    for row in rows:
        for kind, count in row.get("faults", {}).items():
            fault_census[kind] = fault_census.get(kind, 0) + count
    shed_census = {}
    for row in shed_rows:
        reason = row.get("shed_reason") or "unknown"
        shed_census[reason] = shed_census.get(reason, 0) + 1
    report["chaos"] = {
        "faults": fault_census,
        "requeues": sum(r.get("requeues", 0) for r in rows),
        "shed_by_reason": shed_census,
        "requests_touched": len(chaos_rows),
        "goodput": {
            "chaos": _goodput(chaos_rows, len(chaos_rows)),
            "clean": _goodput(clean_rows, len(clean_rows)),
        },
    }
    # graftflex: resize census (global resize events, stamped from/to/
    # reason) + per-geometry occupancy split (tick_commit `slots`
    # stamps). The split is what keeps an autoscale-vs-fixed A/B
    # honest: a mean over mixed widths hides that the narrow rung ran
    # full while the wide rung coasted.
    resize_events = sorted(
        (e for e in globals_ if e["event"] == "resize"),
        key=lambda e: e["_monotonic"])
    by_geom = {}
    for events in lifecycles.values():
        for event in events:
            if (event["event"] == "tick_commit"
                    and event.get("slots") is not None
                    and event.get("active_slots") is not None):
                by_geom.setdefault(int(event["slots"]), []).append(
                    event["active_slots"])
    report["geometry"] = {
        "resizes": {
            "grow": sum(1 for e in resize_events
                        if e.get("to", 0) > e.get("from", 0)),
            "shrink": sum(1 for e in resize_events
                          if e.get("to", 0) < e.get("from", 0)),
        },
        "resize_events": [
            {"from": e.get("from"), "to": e.get("to"),
             "reason": e.get("reason"), "tick": e.get("tick")}
            for e in resize_events],
        "occupancy_by_slots": {
            str(slots): {
                "tick_commits": len(vals),
                "active_mean": sum(vals) / len(vals),
                "utilization": sum(vals) / (len(vals) * slots),
            }
            for slots, vals in sorted(by_geom.items())},
    }
    return report


def sweep_events(by_process):
    """graftsweep records -> payload events (emit-time ordered), each
    stamped "_monotonic"/"_time" like the reqtrace gatherer."""
    out = []
    for records in by_process.values():
        for record in records:
            if record.get("kind") != "graftsweep":
                continue
            payload = record.get("payload")
            if not isinstance(payload, dict) or "event" not in payload:
                continue
            event = dict(payload)
            event["_monotonic"] = float(record.get("monotonic", 0.0))
            event["_time"] = record.get("time")
            out.append(event)
    out.sort(key=lambda e: e["_monotonic"])
    return out


def sweep_report(events):
    """graftsweep events -> the sweep report dict
    (`cloud_tpu.sweep_report.v1`).

    One entry per sweep name seen in the log. Per-trial rows come from
    each trial's single `complete` event (the authoritative ledger:
    status, score, guard census, compile census, lineage); the
    lifecycle stream cross-checks it — a `trial_start` with no
    `complete` is an ORPHAN (a lost trial: the engine guarantees every
    trial terminal, so CI asserts this list empty), and per-trial
    rung_report/promote/fault/resume counts are reconciled into the
    row so the report and the raw log can't silently disagree.
    """
    sweeps = {}
    order = []
    for event in events:
        name = event.get("sweep", "sweep")
        if name not in sweeps:
            order.append(name)
            sweeps[name] = {"start": None, "end": None, "complete": {},
                            "started": [], "counts": {}}
        agg = sweeps[name]
        etype = event["event"]
        if etype == "sweep_start":
            agg["start"] = event
        elif etype == "sweep_complete":
            agg["end"] = event
        elif etype == "trial_start":
            agg["started"].append(event["trial"])
        elif etype == "complete":
            agg["complete"][event["trial"]] = event
        if etype in ("rung_report", "promote", "prune", "fault",
                     "resume"):
            per_trial = agg["counts"].setdefault(event["trial"], {})
            per_trial[etype] = per_trial.get(etype, 0) + 1

    report = {"format": "cloud_tpu.sweep_report.v1", "sweeps": []}
    for name in order:
        agg = sweeps[name]
        start = agg["start"] or {}
        end = agg["end"] or {}
        objective = start.get("objective") or {}
        direction = objective.get("direction", "min")
        trials = []
        for trial_id in sorted(set(agg["started"])
                               | set(agg["complete"])):
            complete = agg["complete"].get(trial_id)
            row = {"trial": trial_id}
            if complete is not None:
                row.update({k: v for k, v in complete.items()
                            if not k.startswith("_")
                            and k not in ("event", "sweep")})
            row["events"] = agg["counts"].get(trial_id, {})
            trials.append(row)
        orphans = sorted(set(agg["started"]) - set(agg["complete"]))
        scored = [t for t in trials
                  if t.get("status") == "COMPLETED"
                  and t.get("score") is not None]
        best = None
        if scored:
            best = (max if direction == "max" else min)(
                scored, key=lambda t: t["score"])
        statuses = {}
        for t in trials:
            status = t.get("status", "ORPHANED")
            statuses[status] = statuses.get(status, 0) + 1
        cold = [t for t in trials if t.get("cold")]
        warm = [t for t in trials if t.get("cold") is False]

        def _total(rows, key):
            return sum(t.get(key) or 0 for t in rows)

        fault_kinds = {}
        for t in trials:
            for kind in t.get("fault_kinds") or ():
                fault_kinds[kind] = fault_kinds.get(kind, 0) + 1
        wall_s = end.get("wall_s")
        train_s = end.get("train_s")
        sweep_entry = {
            "sweep": name,
            "oracle": start.get("oracle"),
            "scheduler": start.get("scheduler"),
            "objective": objective or None,
            "budgets": start.get("budgets"),
            "max_trials": start.get("max_trials"),
            "directory": start.get("directory"),
            "complete": agg["end"] is not None,
            "trials": trials,
            "statuses": statuses,
            "orphans": orphans,
            "best": ({"trial": best["trial"], "score": best["score"],
                      "hp": best.get("hp"), "seed": best.get("seed"),
                      "rungs": best.get("rungs")}
                     if best is not None else None),
            "census": {
                "faults": _total(trials, "faults"),
                "retries": _total(trials, "retries"),
                "rollbacks": _total(trials, "rollbacks"),
                "resumes": _total(trials, "resumes"),
                "by_kind": fault_kinds,
            },
            "compile": {
                "cold_trials": len(cold),
                "warm_trials": len(warm),
                "cold_seconds": round(_total(cold, "compile_seconds"),
                                      6),
                "warm_seconds": round(_total(warm, "compile_seconds"),
                                      6),
                "warm_new_compiles": _total(warm, "new_compiles"),
                "warm_new_traces": _total(warm, "new_traces"),
            },
            "wall": {
                "sweep_s": wall_s,
                "train_s": train_s,
                "overhead_s": (round(wall_s - train_s, 6)
                               if wall_s is not None
                               and train_s is not None else None),
            },
        }
        report["sweeps"].append(sweep_entry)
    return report


def serve_trace_lane(lifecycles, globals_=(), pid=0):
    """Per-request waterfall as Chrome trace events on one pid lane.

    tid 0 is the global cache lane (prefix_evict instants); each
    request gets its own tid (ordered by first event) named after its
    rid, with its phases tiled as "X" events and tick_commit/fail as
    instants. Timestamps are microseconds from the earliest reqtrace
    event, so the lane lines up with span lanes from the same process.
    """
    monos = [e["_monotonic"] for events in lifecycles.values()
             for e in events]
    monos.extend(e["_monotonic"] for e in globals_)
    if not monos:
        return []
    t0 = min(monos)

    def _us(t):
        return (t - t0) * 1e6

    events = [
        {"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
         "args": {"name": "graftserve requests"}},
        {"ph": "M", "pid": pid, "tid": 0, "name": "process_sort_index",
         "args": {"sort_index": pid}},
        {"ph": "M", "pid": pid, "tid": 0, "name": "thread_name",
         "args": {"name": "prefix cache"}},
    ]
    for event in globals_:
        events.append({"ph": "i", "pid": pid, "tid": 0, "s": "t",
                       "name": event["event"], "ts": _us(event["_monotonic"]),
                       "args": {k: v for k, v in event.items()
                                if not k.startswith("_")}})
    # graftflex geometry lane: a Perfetto counter stepping at each
    # resize, seeded with the pre-resize width (the first event's
    # `from`) so the rung the run STARTED on is visible too. Fixed-
    # geometry runs have no resize events and draw no lane.
    resizes = sorted((e for e in globals_ if e["event"] == "resize"),
                     key=lambda e: e["_monotonic"])
    if resizes:
        events.append({"ph": "C", "pid": pid, "tid": 0,
                       "name": "slot_count", "ts": _us(t0),
                       "args": {"slots": resizes[0].get("from")}})
        for event in resizes:
            events.append({"ph": "C", "pid": pid, "tid": 0,
                           "name": "slot_count",
                           "ts": _us(event["_monotonic"]),
                           "args": {"slots": event.get("to")}})
    ordered = sorted(lifecycles.items(),
                     key=lambda kv: kv[1][0]["_monotonic"])
    for tid, (key, levents) in enumerate(ordered, start=1):
        rid = key.rsplit("/", 1)[-1]
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name", "args": {"name": rid}})
        first = {}
        for event in levents:
            first.setdefault(event["event"], event)
        present = [(name, first[name]["_monotonic"])
                   for name in _BOUNDARIES if name in first]
        for (_, t_a), (name_b, t_b) in zip(present, present[1:]):
            args = {k: v for k, v in first[name_b].items()
                    if not k.startswith("_") and k != "rid"}
            events.append({"ph": "X", "pid": pid, "tid": tid,
                           "name": _PHASE_OF[name_b], "cat": "reqtrace",
                           "ts": _us(t_a),
                           "dur": max((t_b - t_a) * 1e6, 0.0),
                           "args": args})
        for event in levents:
            if event["event"] in ("tick_commit", "fail"):
                events.append({"ph": "i", "pid": pid, "tid": tid,
                               "s": "t", "name": event["event"],
                               "ts": _us(event["_monotonic"]),
                               "args": {k: v for k, v in event.items()
                                        if not k.startswith("_")
                                        and k != "rid"}})
    return events


def collect(inputs, out_dir, serve=False, slo_ttft=None, slo_tpot=None,
            sweep=False):
    """The full pass: discover -> group -> report -> merge -> write.
    Returns the fleet report dict (with an extra "outputs" section
    naming what was written). `serve=True` additionally rolls reqtrace
    records into serve_report.json and a waterfall lane in trace.json;
    `sweep=True` rolls graftsweep records into sweep_report.json.
    """
    jsonl_paths, trace_paths = discover_inputs(inputs)
    by_process, corrupt = load_process_records(jsonl_paths)
    report = fleet_report(by_process, corrupt)
    os.makedirs(out_dir, exist_ok=True)
    outputs = {}

    lifecycles, globals_ = {}, []
    if serve:
        lifecycles, globals_ = request_lifecycles(by_process)
        sreport = serve_report(lifecycles, globals_,
                               slo_ttft=slo_ttft, slo_tpot=slo_tpot)
        serve_path = os.path.join(out_dir, "serve_report.json")
        with open(serve_path, "w") as f:
            json.dump(sreport, f, indent=2, sort_keys=True)
            f.write("\n")
        outputs["serve_report"] = serve_path
        report["serve"] = {
            "requests": sreport["requests"],
            "goodput": sreport["goodput"],
            "prefill_chunks": sreport["prefill_chunks"],
        }

    if sweep:
        swreport = sweep_report(sweep_events(by_process))
        sweep_path = os.path.join(out_dir, "sweep_report.json")
        with open(sweep_path, "w") as f:
            json.dump(swreport, f, indent=2, sort_keys=True)
            f.write("\n")
        outputs["sweep_report"] = sweep_path
        report["sweep"] = {
            "sweeps": len(swreport["sweeps"]),
            "trials": sum(len(s["trials"])
                          for s in swreport["sweeps"]),
            "orphans": sum(len(s["orphans"])
                           for s in swreport["sweeps"]),
            "faults": sum(s["census"]["faults"]
                          for s in swreport["sweeps"]),
            "best": [s["best"] for s in swreport["sweeps"]],
        }

    report_path = os.path.join(out_dir, "fleet_report.json")
    with open(report_path, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    outputs["report"] = report_path

    if trace_paths or (serve and lifecycles):
        trace, lanes = merge_traces(trace_paths)
        if serve:
            trace["traceEvents"].extend(
                serve_trace_lane(lifecycles, globals_, pid=len(lanes)))
        trace_path = os.path.join(out_dir, "trace.json")
        with open(trace_path, "w") as f:
            json.dump(trace, f)
        outputs["trace"] = trace_path
        outputs["lanes"] = len(lanes) + (1 if serve and lifecycles
                                         else 0)

    prom_path = os.path.join(out_dir, "fleet.prom")
    with open(prom_path, "w") as f:
        f.write(render_fleet_prometheus(report))
    outputs["prom"] = prom_path

    report["outputs"] = outputs
    return report


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m cloud_tpu.monitoring.collect",
        description="Merge per-process cloud_tpu telemetry into one "
                    "fleet report + multi-lane trace.")
    parser.add_argument("inputs", nargs="+",
                        help="telemetry directories, *.jsonl logs, or "
                             "trace.json files")
    parser.add_argument("--out", default="fleet",
                        help="output directory (default ./fleet)")
    parser.add_argument("--serve", action="store_true",
                        help="also roll reqtrace records into "
                             "serve_report.json + a waterfall lane")
    parser.add_argument("--slo-ttft", type=float, default=None,
                        help="goodput TTFT target, seconds")
    parser.add_argument("--slo-tpot", type=float, default=None,
                        help="goodput per-token target, seconds")
    parser.add_argument("--sweep", action="store_true",
                        help="also roll graftsweep trial events into "
                             "sweep_report.json")
    args = parser.parse_args(argv)
    report = collect(args.inputs, args.out, serve=args.serve,
                     slo_ttft=args.slo_ttft, slo_tpot=args.slo_tpot,
                     sweep=args.sweep)
    fleet = report["fleet"]
    print("fleet: {} process(es)".format(fleet["process_count"]))
    serve = report.get("serve")
    if serve is not None:
        reqs = serve["requests"]
        print("serve: {} submitted / {} completed / {} failed / {} "
              "orphaned, goodput {}".format(
                  reqs["submitted"], reqs["completed"], reqs["failed"],
                  reqs["orphaned"], serve["goodput"]["overall"]))
        chunks = serve.get("prefill_chunks") or {}
        if chunks.get("chunk_dispatches"):
            print("serve: chunked prefill on {} request(s) "
                  "({} chunk dispatch(es))".format(
                      chunks["chunked_requests"],
                      chunks["chunk_dispatches"]))
    sweep = report.get("sweep")
    if sweep is not None:
        best = [b for b in sweep["best"] if b]
        print("sweep: {} sweep(s), {} trial(s), {} orphan(s), {} "
              "fault(s){}".format(
                  sweep["sweeps"], sweep["trials"], sweep["orphans"],
                  sweep["faults"],
                  ", best {} = {}".format(best[0]["trial"],
                                          best[0]["score"])
                  if best else ""))
    if "step_p50_skew_pct" in fleet:
        print("step p50 skew: {:.1f}% (straggler: {})".format(
            fleet["step_p50_skew_pct"], fleet["straggler"]))
    for name in fleet.get("dead", ()):
        print("DEAD: {}".format(name))
    for path, count in sorted(
            (report.get("corrupt_inputs") or {}).items()):
        print("torn input: {} ({} corrupt line(s))".format(
            path, "unreadable" if count < 0 else count))
    for key in ("report", "serve_report", "sweep_report", "trace",
                "prom"):
        if key in report["outputs"]:
            print("wrote {}".format(report["outputs"][key]))
    return 0 if fleet["process_count"] else 1


if __name__ == "__main__":
    sys.exit(main())
