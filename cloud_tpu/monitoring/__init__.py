"""Runtime metrics + Cloud Monitoring export (native C++ core).

Reference parity: the one native component (SURVEY §2.2 N1-N5) — a
whitelisted, env-gated, 10s-periodic exporter of runtime metrics to
Cloud Monitoring, rebuilt against this framework's own registry.
"""

from cloud_tpu.monitoring.native import (config_debug_string,
                                         counter_increment, export_count,
                                         flush, gauge_set,
                                         histogram_observe,
                                         native_available, reset_for_testing,
                                         set_description, snapshot_json,
                                         start_exporter, stop_exporter)

# Canonical runtime metric names (the default whitelist in
# src/cpp/monitoring/config.cc).
TRAINING_STEPS = "/cloud_tpu/training/steps"
TRAINING_EXAMPLES = "/cloud_tpu/training/examples"
STEP_TIME_HISTOGRAM = "/cloud_tpu/training/step_time_usecs_histogram"

STEP_TIME_BOUNDS = [1e3, 1e4, 1e5, 1e6, 1e7, 1e8]


def __getattr__(name):
    # Lazy: profiler pulls in jax + the training stack, which metric-only
    # consumers of this package should not pay for; the graftscope
    # modules (telemetry/spans/export) stay unimported until someone
    # actually enables telemetry — the zero-cost-when-off discipline
    # starts at import time.
    if name in ("profiler", "telemetry", "spans", "export", "watch",
                "collect"):
        import importlib
        return importlib.import_module("cloud_tpu.monitoring." + name)
    raise AttributeError(name)
