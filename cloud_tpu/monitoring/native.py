"""ctypes binding to the native monitoring library.

The reference links its exporter into the TF runtime as a C++ plugin
(reference src/cpp/monitoring/stackdriver_exporter.cc:128
REGISTER_TF_METRICS_EXPORTER). Here the native library is loaded into
the Python process via ctypes (pybind11 is not in this image) and the
framework emits runtime metrics through it. A pure-Python fallback
registry keeps the API alive when the shared library has not been built.
"""

import ctypes
import json
import os
import threading

_LIB_ENV = "CLOUD_TPU_MONITORING_LIB"
_LIB_NAME = "libcloud_tpu_monitoring.so"

# C-ABI transport signature: int (*)(const char* method, const char* json).
_TRANSPORT_CFUNC = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p,
                                    ctypes.c_char_p)


def _candidate_paths():
    env = os.environ.get(_LIB_ENV)
    if env:
        yield env
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    yield os.path.join(here, _LIB_NAME)
    yield os.path.join(repo, "src", "cpp", "monitoring", "build", _LIB_NAME)


def _load():
    for path in _candidate_paths():
        if not os.path.exists(path):
            continue
        try:
            lib = ctypes.CDLL(path)
            lib.cloud_tpu_counter_increment.argtypes = [
                ctypes.c_char_p, ctypes.c_int64]
            lib.cloud_tpu_gauge_set.argtypes = [
                ctypes.c_char_p, ctypes.c_double]
            lib.cloud_tpu_histogram_observe.argtypes = [
                ctypes.c_char_p, ctypes.c_double,
                ctypes.POINTER(ctypes.c_double), ctypes.c_int]
            lib.cloud_tpu_metric_set_description.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p]
            lib.cloud_tpu_snapshot_json.restype = ctypes.c_void_p
            lib.cloud_tpu_config_debug_string.restype = ctypes.c_void_p
            lib.cloud_tpu_free.argtypes = [ctypes.c_void_p]
            lib.cloud_tpu_exporter_start.argtypes = [ctypes.c_int64]
            lib.cloud_tpu_exporter_start.restype = ctypes.c_int
            lib.cloud_tpu_exporter_export_count.restype = ctypes.c_int64
            lib.cloud_tpu_set_transport.argtypes = [_TRANSPORT_CFUNC]
            lib.cloud_tpu_http_transport_available.restype = ctypes.c_int
            return lib
        except (OSError, AttributeError):
            # Unloadable or stale .so (missing symbols): keep looking,
            # fall back to Python.
            continue
    return None


_lib = _load()


class _PyFallback:
    """Minimal in-process registry mirroring the C API semantics."""

    def __init__(self):
        self._mu = threading.Lock()
        self.counters = {}
        self.gauges = {}
        self.histograms = {}

    def counter_increment(self, name, delta):
        with self._mu:
            self.counters[name] = self.counters.get(name, 0) + delta

    def gauge_set(self, name, value):
        with self._mu:
            self.gauges[name] = value

    def histogram_observe(self, name, value, bounds):
        with self._mu:
            h = self.histograms.setdefault(
                name, {"bounds": list(bounds), "values": []})
            h["values"].append(value)

    def snapshot_json(self):
        with self._mu:
            project = os.environ.get("CLOUD_TPU_MONITORING_PROJECT_ID", "")
            series = []
            for name, value in self.counters.items():
                series.append({
                    "metric": {"type":
                               "custom.googleapis.com" + name},
                    "metricKind": "CUMULATIVE",
                    "valueType": "INT64",
                    "points": [{"value": {"int64Value": value}}],
                })
            for name, value in self.gauges.items():
                series.append({
                    "metric": {"type":
                               "custom.googleapis.com" + name},
                    "metricKind": "GAUGE",
                    "valueType": "DOUBLE",
                    "points": [{"value": {"doubleValue": value}}],
                })
            for name, h in self.histograms.items():
                values = h["values"]
                count = len(values)
                mean = sum(values) / count if count else 0.0
                series.append({
                    "metric": {"type":
                               "custom.googleapis.com" + name},
                    "metricKind": "CUMULATIVE",
                    "valueType": "DISTRIBUTION",
                    "points": [{"value": {"distributionValue": {
                        "count": count,
                        "mean": mean,
                        "bucketOptions": {"explicitBuckets": {
                            "bounds": h["bounds"]}},
                    }}}],
                })
            if not series:
                return ""
            return json.dumps(
                {"name": "projects/" + project, "timeSeries": series})


_fallback = _PyFallback()


def native_available():
    return _lib is not None


def counter_increment(name, delta=1):
    if _lib is not None:
        _lib.cloud_tpu_counter_increment(name.encode(), int(delta))
    else:
        _fallback.counter_increment(name, delta)


def gauge_set(name, value):
    if _lib is not None:
        _lib.cloud_tpu_gauge_set(name.encode(), float(value))
    else:
        _fallback.gauge_set(name, value)


def histogram_observe(name, value, bounds):
    if _lib is not None:
        arr = (ctypes.c_double * len(bounds))(*bounds)
        _lib.cloud_tpu_histogram_observe(name.encode(), float(value), arr,
                                         len(bounds))
    else:
        _fallback.histogram_observe(name, value, bounds)


def set_description(name, description):
    if _lib is not None:
        _lib.cloud_tpu_metric_set_description(name.encode(),
                                              description.encode())


def snapshot_json():
    """Serialized CreateTimeSeries request for current metrics."""
    if _lib is not None:
        ptr = _lib.cloud_tpu_snapshot_json()
        try:
            return ctypes.string_at(ptr).decode()
        finally:
            _lib.cloud_tpu_free(ptr)
    return _fallback.snapshot_json()


def config_debug_string():
    if _lib is not None:
        ptr = _lib.cloud_tpu_config_debug_string()
        try:
            return ctypes.string_at(ptr).decode()
        finally:
            _lib.cloud_tpu_free(ptr)
    return "python-fallback"


def start_exporter(interval_micros=10_000_000):
    """Starts the native periodic exporter (no-op without the library or
    when CLOUD_TPU_MONITORING_ENABLED != true)."""
    if _lib is None:
        return False
    return bool(_lib.cloud_tpu_exporter_start(int(interval_micros)))


def flush():
    if _lib is not None:
        _lib.cloud_tpu_exporter_flush()


def export_count():
    return _lib.cloud_tpu_exporter_export_count() if _lib is not None else 0


def stop_exporter():
    if _lib is not None:
        _lib.cloud_tpu_exporter_stop()


# Keepalive for every thunk ever registered: an in-flight native send
# may still hold a pointer loaded before a swap, so old trampolines are
# never freed (a few dozen bytes per set_transport call, by design).
_transport_keepalive = []


def set_transport(fn):
    """Routes native exporter sends through a Python callable.

    `fn(method: str, json: str) -> bool` with method one of
    "CreateTimeSeries" / "CreateMetricDescriptor". The C++ exporter
    keeps owning collection/filtering/request synthesis; only the final
    send crosses back into Python (e.g. to reuse an authenticated
    google-api client). Pass None to restore the env-selected transport
    (file, or http when CLOUD_TPU_MONITORING_TRANSPORT=http).
    """
    if _lib is None:
        return False
    if fn is None:
        _lib.cloud_tpu_set_transport(_TRANSPORT_CFUNC())
        return True

    def _bridge(method, payload):
        try:
            return 1 if fn(method.decode(), payload.decode()) else 0
        except Exception:  # never let an exception cross the C boundary
            return 0

    thunk = _TRANSPORT_CFUNC(_bridge)
    _transport_keepalive.append(thunk)
    _lib.cloud_tpu_set_transport(thunk)
    return True


def http_transport_available():
    """True when the native library can reach libcurl for real sends."""
    if _lib is None:
        return False
    return bool(_lib.cloud_tpu_http_transport_available())


def google_auth_transport(session=None):
    """Transport callable that POSTs via an authenticated google client.

    The Python-side default-credentials path (reference
    stackdriver_client.cc:56-58): pair with `set_transport`. `session`
    defaults to `google.auth` application-default credentials wrapped in
    an AuthorizedSession; inject a fake for tests.
    """
    import json as json_lib

    if session is None:
        import google.auth
        from google.auth.transport.requests import AuthorizedSession

        credentials, project = google.auth.default(
            scopes=["https://www.googleapis.com/auth/monitoring.write"])
        session = AuthorizedSession(credentials)

    endpoint = os.environ.get("CLOUD_TPU_MONITORING_ENDPOINT",
                              "https://monitoring.googleapis.com")

    def _send(method, payload):
        # The builders emit gRPC-shaped wrappers; the REST bindings put
        # the project in the URL and take the bare payload as body
        # (metricDescriptors.create: a MetricDescriptor;
        # timeSeries.create: {"timeSeries": [...]}).
        body = json_lib.loads(payload)
        project_path = body.pop("name", "")
        if method == "CreateMetricDescriptor":
            path = "metricDescriptors"
            body = body.get("metricDescriptor", body)
        else:
            path = "timeSeries"
        url = "{}/v3/{}/{}".format(endpoint, project_path, path)
        response = session.post(url, json=body, timeout=15)
        return 200 <= response.status_code < 300

    return _send


def reset_for_testing():
    if _lib is not None:
        _lib.cloud_tpu_registry_reset()
        _lib.cloud_tpu_config_reset()
    else:
        global _fallback
        _fallback = _PyFallback()
