"""graftscope exporters: Prometheus textfile, Chrome trace, JSONL, native.

Every backend renders ONE registry snapshot (telemetry.Registry
.snapshot() — plain dicts, no locks held) so a flush pass is consistent
across files. Flushes run on a background daemon thread behind a
bounded queue — the PR 2 async-reader pattern — so the training loop
never blocks on disk: non-waiting requests coalesce (a queued flush
already covers them) and `env_scope()` issues one blocking flush at
exit so artifacts exist when fit() returns.

Outputs under the telemetry directory:
    trace.json       Chrome trace-event JSON (open in Perfetto)
    metrics.prom     Prometheus textfile-collector format
    telemetry.jsonl  JSONL rollups via utils/events (one line per flush)
plus the monitoring/native.py registry as a third (in-process) backend.
"""

import json
import logging
import os
import queue
import threading

logger = logging.getLogger("cloud_tpu")

__all__ = ["FlushWorker", "PrometheusTextfileExporter",
           "ChromeTraceExporter", "JsonlExporter", "NativeExporter",
           "default_exporters", "render_prometheus"]

_CLOSE = object()


class FlushWorker:
    """Bounded-queue background flusher (async-reader discipline).

    `request()` is lossy by design: if a flush is already queued the
    new request is dropped — that queued pass will export strictly
    newer state than the caller just observed. `request(wait=True)`
    always enqueues (blocking on the bounded queue if needed) and
    returns only after its pass completed. Flush errors are logged,
    never raised into the caller.
    """

    _QUEUE_DEPTH = 2

    def __init__(self, flush_fn, name="cloud-tpu-telemetry-flush"):
        self._flush_fn = flush_fn
        self._queue = queue.Queue(maxsize=self._QUEUE_DEPTH)
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            item = self._queue.get()
            if item is _CLOSE:
                return
            try:
                self._flush_fn()
            except Exception:
                logger.debug("telemetry flush failed", exc_info=True)
            finally:
                if item is not None:
                    item.set()

    def request(self, wait=False):
        if wait:
            done = threading.Event()
            self._queue.put(done)
            done.wait()
            return
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass  # a queued pass will export newer state anyway

    def close(self, flush=True):
        """Stops the worker; with flush=True runs one final blocking
        pass first."""
        if flush:
            self.request(wait=True)
        self._queue.put(_CLOSE)
        self._thread.join(timeout=10)


def _format_number(value):
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    return repr(float(value))


def render_prometheus(snapshot):
    """Registry snapshot -> Prometheus textfile-collector text.

    Histograms render the standard _bucket{le=}/_sum/_count series plus
    separate `<name>_p50/_p95/_p99` gauges — pre-computed quantiles are
    a different metric type than the histogram itself, and mixing them
    as {quantile=} labels on a histogram is invalid exposition format.
    """
    lines = []
    for name in sorted(snapshot.get("counters", ())):
        value = snapshot["counters"][name]
        lines.append("# TYPE {} counter".format(name))
        lines.append("{} {}".format(name, _format_number(value)))
    for name in sorted(snapshot.get("gauges", ())):
        value = snapshot["gauges"][name]
        lines.append("# TYPE {} gauge".format(name))
        lines.append("{} {}".format(name, _format_number(value)))
    for name in sorted(snapshot.get("histograms", ())):
        hist = snapshot["histograms"][name]
        lines.append("# TYPE {} histogram".format(name))
        cumulative = 0
        for bound, count in zip(hist["bounds"], hist["counts"]):
            cumulative += count
            lines.append('{}_bucket{{le="{:g}"}} {}'.format(
                name, bound, cumulative))
        cumulative += hist["counts"][len(hist["bounds"])]
        lines.append('{}_bucket{{le="+Inf"}} {}'.format(name, cumulative))
        lines.append("{}_sum {}".format(name,
                                        _format_number(hist["sum"])))
        lines.append("{}_count {}".format(name, hist["count"]))
        for quantile in ("p50", "p95", "p99"):
            qname = "{}_{}".format(name, quantile)
            lines.append("# TYPE {} gauge".format(qname))
            lines.append("{} {}".format(
                qname, _format_number(hist[quantile])))
    return "\n".join(lines) + "\n"


class PrometheusTextfileExporter:
    """Atomic textfile writes (tmp + rename): the node-exporter
    textfile collector must never read a half-written scrape."""

    def __init__(self, path):
        self.path = path

    def export(self, telemetry):
        text = render_prometheus(telemetry.registry.snapshot())
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, self.path)


class ChromeTraceExporter:
    def __init__(self, path):
        self.path = path

    def export(self, telemetry):
        tracer = telemetry.tracer
        if tracer is not None:
            tracer.write(self.path)


class JsonlExporter:
    """One JSONL rollup line per flush via utils/events, carrying the
    counter/gauge/percentile view plus any active graftsan
    `site_counts()` (duck-typed off the runtime observer stack, so the
    line attributes counter movement to file:line when a sanitizer is
    stacked alongside telemetry)."""

    def __init__(self, path):
        self.path = path

    def export(self, telemetry):
        from cloud_tpu.parallel import runtime
        from cloud_tpu.utils import events

        snapshot = telemetry.registry.snapshot()
        payload = {
            "counters": snapshot["counters"],
            "gauges": snapshot["gauges"],
            "histograms": {
                name: {k: hist[k]
                       for k in ("count", "sum", "p50", "p95", "p99")}
                for name, hist in snapshot["histograms"].items()
            },
        }
        for observer in runtime.observers():
            site_counts = getattr(observer, "site_counts", None)
            if callable(site_counts):
                try:
                    payload["sanitizer_sites"] = site_counts()
                except Exception:
                    pass
                break
        events.log_job_event("telemetry", payload, path=self.path)


class NativeExporter:
    """Mirrors the registry into monitoring/native.py (the ctypes C++
    exporter, or its pure-Python fallback) as a third backend.

    The native counter API is increment-only, so this exporter keeps a
    last-pushed table and pushes deltas; gauges and histogram
    percentiles are set directly under `/cloud_tpu/telemetry/...`
    metric paths (the native naming convention).
    """

    def __init__(self):
        self._pushed = {}

    @staticmethod
    def _native_name(name):
        # cloud_tpu_h2d_bytes_total -> /cloud_tpu/telemetry/h2d_bytes_total
        stripped = name[len("cloud_tpu_"):] if name.startswith(
            "cloud_tpu_") else name
        return "/cloud_tpu/telemetry/" + stripped

    def export(self, telemetry):
        from cloud_tpu.monitoring import native

        snapshot = telemetry.registry.snapshot()
        for name, value in snapshot["counters"].items():
            delta = value - self._pushed.get(name, 0)
            if delta:
                native.counter_increment(self._native_name(name), delta)
                self._pushed[name] = value
        for name, value in snapshot["gauges"].items():
            native.gauge_set(self._native_name(name), value)
        for name, hist in snapshot["histograms"].items():
            base = self._native_name(name)
            for quantile in ("p50", "p95", "p99"):
                native.gauge_set("{}/{}".format(base, quantile),
                                 hist[quantile])


class _DebugDumpExporter:
    """Developer aid: full snapshot as pretty JSON next to the trace
    when CLOUD_TPU_TELEMETRY_DEBUG is set."""

    def __init__(self, path):
        self.path = path

    def export(self, telemetry):
        with open(self.path, "w") as f:
            json.dump(telemetry.registry.snapshot(), f, indent=2,
                      sort_keys=True)


def default_exporters(out_dir):
    exporters = [
        ChromeTraceExporter(os.path.join(out_dir, "trace.json")),
        PrometheusTextfileExporter(os.path.join(out_dir,
                                                "metrics.prom")),
        JsonlExporter(os.path.join(out_dir, "telemetry.jsonl")),
        NativeExporter(),
    ]
    if os.environ.get("CLOUD_TPU_TELEMETRY_DEBUG"):
        exporters.append(_DebugDumpExporter(
            os.path.join(out_dir, "registry.json")))
    return exporters
