"""graftlens request tracing: per-request lifecycle events for graftserve.

Every request admitted to the Scheduler gets a process-unique request id
(rid) stamped at ``submit()``; the serving path then annotates its
lifecycle as typed events::

    submitted -> queued -> radix_probe -> pages_reserved -> prefill
              -> slot_insert -> tick_commit* -> complete | fail | shed

With chunked prefill enabled (``CLOUD_TPU_SERVE_PREFILL_CHUNK``), the
prefill phase is tiled by per-chunk events emitted at each dispatch::

    pages_reserved -> prefill_chunk{i, n, tokens, dur_s}*
                   -> prefill{..., chunks}

``prefill_chunk`` events are sub-phase detail INSIDE the
(pages_reserved, prefill] span, not lifecycle boundaries — phase sums
still telescope to the submitted -> complete wall time with or without
them, and ``collect --serve`` audits exactly that.

graftpack (the KV memory hierarchy) adds page-tier movement events:
``page_demote{pages, tokens}`` fires at a request's completion when its
written prefix pages snapshot to the host tier, and
``page_promote{pages, prefix_len}`` fires INSIDE a later request's
admission when host pages are copied back ahead of its suffix prefill —
a promoted request's ``prefill`` event then carries the promoted
``prefix_len``, which is how ``collect --serve`` splits follow-up-turn
TTFT into promoted vs device-cache-hit vs re-prefill classes.
``page_demote`` lands between the final tick and ``complete`` on the
same rid; neither event is a lifecycle boundary, so phase sums
telescope unchanged.

graftflex (elastic tick geometry) adds a GLOBAL event — emitted with
``rid=None`` because a resize belongs to the replica, not to any one
request: ``resize{from, to, reason, tick}`` fires at the tick
boundary where the slot count moves one ladder rung (``reason`` is
``grow``/``shrink`` for policy resizes, ``warmup`` for the ladder walk,
or a caller-supplied tag for forced resizes). A multi-rung forced jump
emits one event per adjacent step, so the event stream replays the
exact executable dispatches. ``tick_commit`` events carry a ``slots``
field stamping the geometry they committed under, which is how
``collect --serve`` splits occupancy per rung and draws the slot-count
counter lane; per-request phase sums are untouched (a resize is not a
lifecycle boundary — in-flight rows migrate bit-identically).

graftstorm (serving chaos) adds mid-lifecycle fault events: a chaos
injection that hits an in-flight request emits ``slot_fault`` (with the
taxonomy ``kind`` and the victim slot) followed by ``requeue`` (with
``tokens_done``, the retained progress) — the request then re-enters at
``pages_reserved``/``prefill`` and still terminates normally, so a
requeued rid is NOT an orphan. ``shed`` (with ``reason`` and
``predicted_ttft``) is the SLO-admission terminal: refused by policy,
never prefilled.

Events are buffered in-process and flushed as ``reqtrace`` JSONL records
whose envelope matches ``cloud_tpu.utils.events`` job-event records
(time / monotonic / host / pid / process_index / kind / payload), so
``read_job_events()`` and the fleet collector consume them unchanged.
``monitoring/collect.py --serve`` rolls them into a per-request waterfall
trace plus ``serve_report.json`` (TTFT/TPOT percentiles, queue-wait
breakdown, SLO goodput).

Zero-cost discipline (same contract as spans.py): when
``CLOUD_TPU_REQTRACE`` is unset nothing is installed — ``get()`` returns
None, the Scheduler stamps no rids and emits no events, and no file or
thread is ever created. The tracer itself never spawns threads either;
buffered lines are appended synchronously on terminal events or when the
buffer fills.
"""

import json
import os
import socket
import sys
import threading
import time

from cloud_tpu.utils import storage

_TRUTHY_OFF = ("", "0", "off", "false", "none")

# Batched per-slot tick commits: one tick_commit event every N engine
# ticks per active slot (overridable via CLOUD_TPU_REQTRACE_TICK_EVERY).
DEFAULT_TICK_EVERY = 8

_tracer = None
_lock = threading.Lock()


def env_enabled():
    """True when CLOUD_TPU_REQTRACE asks for request tracing."""
    value = os.environ.get("CLOUD_TPU_REQTRACE", "")
    return value.strip().lower() not in _TRUTHY_OFF


def default_path():
    base = (os.environ.get("CLOUD_TPU_REQTRACE_DIR")
            or os.environ.get("CLOUD_TPU_TELEMETRY_DIR")
            or os.getcwd())
    return os.path.join(base, "reqtrace.jsonl")


def _process_index():
    env = os.environ.get("CLOUD_TPU_PROCESS_INDEX")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return jax.process_index()
        except Exception:
            pass
    return 0


class RequestTracer:
    """Buffered JSONL emitter for request lifecycle events.

    Thread-safe; shared by the Scheduler's admission and tick threads.
    Never spawns threads of its own — the env-unset pin in CI asserts
    both zero events and zero threads.
    """

    def __init__(self, path=None, tick_every=None, flush_every=64):
        self.path = path or default_path()
        if tick_every is None:
            raw = os.environ.get("CLOUD_TPU_REQTRACE_TICK_EVERY", "")
            try:
                tick_every = int(raw)
            except ValueError:
                tick_every = DEFAULT_TICK_EVERY
        self.tick_every = max(1, int(tick_every))
        self._flush_every = max(1, int(flush_every))
        self._lock = threading.Lock()
        self._buffer = []
        self._next_rid = 0
        self._emitted = 0
        self._host = socket.gethostname()
        self._pid = os.getpid()
        self._process_index = _process_index()
        if not storage.is_gcs_path(self.path):
            parent = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(parent, exist_ok=True)

    def new_request(self):
        """Allocates a process-unique request id ("r000042")."""
        with self._lock:
            rid = "r%06d" % self._next_rid
            self._next_rid += 1
        return rid

    def emit(self, rid, event, **fields):
        """Records one lifecycle event. ``rid=None`` marks a global
        (request-independent) event such as prefix_evict."""
        payload = {"rid": rid, "event": event}
        payload.update(fields)
        record = {
            "time": time.time(),
            "monotonic": time.monotonic(),
            "host": self._host,
            "pid": self._pid,
            "process_index": self._process_index,
            "kind": "reqtrace",
            "payload": payload,
        }
        line = json.dumps(record, sort_keys=True) + "\n"
        terminal = event in ("complete", "fail", "shed")
        with self._lock:
            self._buffer.append(line)
            self._emitted += 1
            if terminal or len(self._buffer) >= self._flush_every:
                self._flush_locked()

    def events_emitted(self):
        with self._lock:
            return self._emitted

    def _flush_locked(self):
        if not self._buffer:
            return
        data = "".join(self._buffer).encode("utf-8")
        self._buffer = []
        storage.append_bytes(self.path, data)

    def flush(self):
        with self._lock:
            self._flush_locked()

    def close(self):
        self.flush()


def install(path=None, tick_every=None):
    """Installs (or replaces) the ambient tracer and returns it."""
    global _tracer
    with _lock:
        previous, _tracer = _tracer, RequestTracer(path=path,
                                                   tick_every=tick_every)
    if previous is not None:
        previous.flush()
    return _tracer


def uninstall():
    """Flushes and removes the ambient tracer; returns it (or None)."""
    global _tracer
    with _lock:
        previous, _tracer = _tracer, None
    if previous is not None:
        previous.flush()
    return previous


def get():
    """The ambient tracer, or None when tracing is off."""
    return _tracer


def maybe_enable():
    """Scheduler.start() seam: returns the installed tracer; installs
    one from the environment when CLOUD_TPU_REQTRACE is set; otherwise
    returns None without touching the filesystem."""
    if _tracer is not None:
        return _tracer
    if not env_enabled():
        return None
    return install()


__all__ = [
    "DEFAULT_TICK_EVERY",
    "RequestTracer",
    "default_path",
    "env_enabled",
    "get",
    "install",
    "maybe_enable",
    "uninstall",
]
