"""graftserve request scheduler: admission, batching, backpressure.

Two threads around a `DecodeEngine`:

- the ADMISSION thread pops submitted requests from a bounded queue in
  FCFS windows, orders each window longest-RADIX-match-first (requests
  whose prompts share the most already-cached pages admit first — they
  are the cheapest TTFT and keep hot prefixes hot; ties fall back to
  longest-prefill-first), reserves KV pages for cache MISSES (BLOCKING
  when the pool is exhausted — backpressure, never OOM; a blocked
  reservation applies LRU eviction pressure to the prefix cache), and
  runs miss prefills off the tick's critical path;
- the TICK thread owns the engine's device state: it admits prefix-
  cache HITS (the hit prefill gathers from the engine's live pool
  cache, which every tick donates — only the tick thread may read it),
  inserts ready prefills into free slots, advances all active slots
  (one committed token per tick, or up to spec_k + 1 with speculative
  decode), fetches the tick output (the serving loop's single counted
  d2h round trip), completes/evicts finished slots, and returns their
  pages.

Prefix sharing (graftshare): every inserted prompt's full pages are
registered in a radix trie (serving/prefixcache.py). A later request
whose prompt shares a prefix maps those pages into its own page table
(pool-refcounted, copy-on-write on divergence) and prefills only its
suffix — TTFT O(prompt) -> O(suffix). The trie's HBM budget is enforced
by LRU eviction of pages no in-flight request holds.

Liveness rides graftwatch: the tick thread beats the installed watchdog
every iteration and polls `watch.check()`, so a stuck tick surfaces as
the watchdog's typed fault (graftwatch blackbox + `BackendUnavailable`)
instead of a silent hang. Throughput/latency ride graftscope: requests
and tokens totals, queue-depth and active-slots gauges, pool/prefix
gauges, and TTFT histograms split by hit/miss (p50/p95/p99 via the
registry snapshot).

Phase labels: the tick thread runs under `runtime.set_phase
("serve_tick")`, the admission thread under "serve_prefill" — distinct
from the training "step" phase, so graftsan GS001 (d2h-in-step-loop)
correctly treats the per-tick fetch as a sanctioned, attributed read.

Request tracing (graftlens): with `CLOUD_TPU_REQTRACE=1` every request
gets a rid at submit() and its lifecycle lands as typed reqtrace JSONL
events (serving/reqtrace.py): submitted -> queued -> radix_probe ->
pages_reserved -> prefill -> slot_insert -> tick_commit* -> complete |
fail. Boundary-event timestamps tile submit..complete, so the waterfall
the collector's --serve mode renders accounts for end-to-end latency.
With the env unset no tracer is installed: rids stay None, no events,
no file, no threads — the PR 6 zero-hooks discipline, test-pinned.
Queue-wait and page-reservation-wait histograms are host-side and
always on (warm-reset like TTFT), feeding `stats()` and ROADMAP item
4's predicted-TTFT admission.

Fault handling (graftstorm): chaos serving injections (analysis/
chaos.py SERVE_KINDS, tick-indexed) are consumed at the top of every
tick iteration. A faulted slot drains through the same fixed-shape
evict scatter finished slots use — the persistent tick never stops —
its pages return to the pool exactly once (prefix-trie references
survive untouched), and its request re-enters the tick thread's ready
deque as a typed requeue: re-prefill from retained prompt + tokens
generated so far, with the slot's ORIGINAL rng schedule re-based via
the engine's `key_override` so the continuation completes bit-identical
to an uninterrupted decode (graftguard's resume discipline, per slot).
A `prefill_fail` releases any reserved pages and retries — transient,
never lost. SLO-aware admission: with `CLOUD_TPU_SERVE_SLO_TTFT` set
(or the `slo_ttft` ctor arg), the admission thread predicts each
candidate's TTFT from the live queue-wait/prefill histograms plus pool
occupancy, and sheds (typed `ServeShed`) or defers
(`CLOUD_TPU_SERVE_SHED=defer`) work it cannot serve within SLO instead
of plain-FCFS admitting it.

Chunked prefill (ROADMAP item 4 tail): with `prefill_chunk=` (or
`CLOUD_TPU_SERVE_PREFILL_CHUNK`) set to a pow2 chunk width, prefills
run as `engine.ChunkedPrefill` continuations interleaved with the
decode tick — at most ONE chunk dispatched per tick-loop iteration, so
a 4k-token arrival costs every resident slot one chunk of extra
tick-to-tick latency instead of the whole prefill. All three prefill
classes chunk (miss, prefix hit via the gather offset, requeue via
key_override), outputs stay bit-identical (the tail chunk runs the
SAME sampling executable a whole prefill of that suffix would), chaos
`prefill_fail` lands on chunk boundaries with completed chunks
retained, and the admission model swaps the whole-prefill p50 for a
per-chunk histogram. The decode-gap histogram (commit-to-commit
interval over active slots) is the p99 this interleave protects —
tick COMPUTE time alone cannot see a tick loop stalled behind a
monolithic prefill.

Elastic tick geometry (graftflex): with a slot-count ladder configured
(`ladder=`, or pow2 rungs derived from `CLOUD_TPU_SERVE_SLOTS_MIN` /
`CLOUD_TPU_SERVE_SLOTS_MAX`), the tick's batch width follows offered
load through pre-warmed per-rung executables: a full rung with waiting
work grows to the next rung at the SAME tick boundary (a slammed
replica widens instead of shedding), a rung whose live set fits the
next rung down shrinks after `resize_quiet_ticks` consecutive quiet
boundaries (hysteresis — oscillating load never flaps). Page tables
are pool-indexed, so a resize gathers slot ROWS only (rng schedules,
eos latches, spec state ride along bit-identical); KV pages never
move, and warmup walks every rung so steady state stays at zero new
traces. Every per-tick stat stamps its geometry, and the admission
predictor can be replaced by an offline model fit from the reqtrace
corpus (`python -m cloud_tpu.serving.admission fit`, loaded via
`CLOUD_TPU_SERVE_ADMISSION_MODEL` at start()).
"""

import collections
import dataclasses
import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional, Sequence

import jax
import numpy as np

from cloud_tpu.monitoring import spans
from cloud_tpu.parallel import runtime
from cloud_tpu.serving import reqtrace
from cloud_tpu.serving.engine import DecodeEngine
from cloud_tpu.serving.faults import (HostTierCorrupt, PoolSqueezed,
                                      PrefillFailed, ServeShed,
                                      SlotEvicted, SlotHang, fault_kind)
from cloud_tpu.serving.kvpool import HostPageTier, PagePool
from cloud_tpu.serving.prefixcache import PrefixCache

#: pool_squeeze hold window: confiscated pages return after this many
#: ticks OR this much wall time, whichever first — the wall-clock bound
#: keeps a squeeze from deadlocking a pool so starved that no slot is
#: active and ticks stop advancing.
SQUEEZE_HOLD_TICKS = 8
SQUEEZE_HOLD_S = 2.0

_OFF_VALUES = ("", "0", "off", "false", "none")


@dataclasses.dataclass
class ServeRequest:
    """One decode request. Semantics (and output) match
    `generate(model, params, prompt[None], max_new_tokens,
    rng=PRNGKey(rng_seed), ...)` exactly — the determinism contract,
    regardless of prefix sharing or speculation."""
    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_token: Optional[int] = None
    rng_seed: int = 0


@dataclasses.dataclass
class ServeResult:
    """A completed request: `tokens` is prompt + continuation, the
    `generate()` row contract. `prefix_len` is the token count served
    from the prefix cache (0 = cold prefill)."""
    tokens: np.ndarray
    ttft_s: float
    latency_s: float
    prefix_len: int = 0


class _Slot:
    __slots__ = ("request", "pages", "emitted", "future", "t_submit",
                 "ttft_s", "prefix_len", "rid", "trace_ticks",
                 "step_keys", "result_prefix_len")

    def __init__(self, request, pages, future, t_submit, ttft_s,
                 prefix_len, rid=None):
        self.request = request
        self.pages = pages
        self.emitted = []
        self.future = future
        self.t_submit = t_submit
        self.ttft_s = ttft_s
        self.prefix_len = prefix_len
        self.rid = rid
        self.trace_ticks = 0  # ticks since the last tick_commit event
        # Retained per-slot rng schedule (the PrefillResult's host
        # uint32[max_new_cap-1, 2] array): a fault after n emitted
        # tokens re-bases the continuation onto rows n-1 (its prefill
        # key) and n.. (its tick schedule) — graftstorm bit-identity.
        self.step_keys = None
        # prefix_len the final ServeResult reports: survives requeue
        # (the continuation cold-prefills, but the REQUEST's cache-hit
        # status is a property of its original admission).
        self.result_prefix_len = prefix_len


class _ReadyItem:
    """A miss-path prefill waiting for a free slot (admission thread
    already ran the prefill and holds the reserved pages)."""
    __slots__ = ("request", "result", "pages", "future", "t_submit",
                 "ttft_s", "rid")

    def __init__(self, request, result, pages, future, t_submit,
                 ttft_s, rid=None):
        self.request = request
        self.result = result
        self.pages = pages
        self.future = future
        self.t_submit = t_submit
        self.ttft_s = ttft_s
        self.rid = rid


class _HitTicket:
    """A prefix-cache hit waiting for the tick thread: no pages, no
    prefill yet — the hit prefill must read the engine's live pool
    cache, which only the tick thread may touch."""
    __slots__ = ("request", "future", "t_submit", "rid", "t_reserve0")

    def __init__(self, request, future, t_submit, rid=None):
        self.request = request
        self.future = future
        self.t_submit = t_submit
        self.rid = rid
        # First reservation attempt: a page-starved hit retries across
        # _insert_ready passes, so the cumulative reserve wait must
        # survive the ticket being re-queued.
        self.t_reserve0 = None


class _RequeueItem:
    """A faulted request re-entering the tick thread's ready deque
    (graftstorm). `request` is the CONTINUATION: original prompt +
    tokens generated so far, max_new reduced by the same count — so
    prompt + emitted at completion reassembles the original row.
    `key`/`rest` are the original schedule rows the continuation's
    prefill and ticks must consume (engine.prefill key_override)."""
    __slots__ = ("request", "key", "rest", "future", "t_submit",
                 "ttft_s", "result_prefix_len", "rid")

    def __init__(self, request, key, rest, future, t_submit, ttft_s,
                 result_prefix_len, rid=None):
        self.request = request
        self.key = key
        self.rest = rest
        self.future = future
        self.t_submit = t_submit
        self.ttft_s = ttft_s
        self.result_prefix_len = result_prefix_len
        self.rid = rid


class _ChunkItem:
    """An in-flight chunked prefill on the tick thread's interleave
    queue: the `engine.ChunkedPrefill` continuation plus everything
    needed to insert (or complete) it when the tail chunk lands.
    `kind` selects the insert variant — "miss" (admission-thread
    reservation, registers in the trie), "hit" (shared + fresh pages,
    CoW partial page, registers), "requeue" (key-override
    continuation: original TTFT carried, no register)."""
    __slots__ = ("kind", "request", "chunked", "pages", "shared",
                 "fresh", "partial_page", "partial_len", "prefix_len",
                 "result_prefix_len", "future", "t_submit", "ttft_s",
                 "rid", "result", "t_prefill0", "counts_pending",
                 "hold_released")

    def __init__(self, kind, request, chunked, future, t_submit,
                 rid=None, pages=(), shared=(), fresh=(),
                 partial_page=None, partial_len=0, prefix_len=0,
                 result_prefix_len=0, ttft_s=0.0):
        self.kind = kind
        self.request = request
        self.chunked = chunked
        self.pages = list(pages)
        self.shared = list(shared)
        self.fresh = list(fresh)
        self.partial_page = partial_page
        self.partial_len = partial_len
        self.prefix_len = prefix_len
        self.result_prefix_len = result_prefix_len
        self.future = future
        self.t_submit = t_submit
        self.ttft_s = ttft_s
        self.rid = rid
        self.result = None       # PrefillResult once the tail chunk ran
        self.t_prefill0 = None   # first chunk dispatch (prefill span)
        self.counts_pending = (kind != "requeue"
                               and request.max_new_tokens > 1)
        self.hold_released = False

    def pages_held(self):
        """Pages the eventual _Slot owns (the CoW partial page is
        freed at insert, never carried into the slot)."""
        if self.kind == "hit":
            return self.shared + self.fresh
        return list(self.pages)

    def all_pages(self):
        """Every page to free if the item dies before insert."""
        held = self.pages_held()
        if self.kind == "hit" and self.partial_len:
            held = held + [self.partial_page]
        return held


def _registry():
    """graftscope registry when telemetry is enabled, else None — the
    decode hooks' zero-cost-when-off discipline."""
    import sys
    telemetry = sys.modules.get("cloud_tpu.monitoring.telemetry")
    if telemetry is None:
        return None
    tele = telemetry.get()
    if tele is None or not tele.active:
        return None
    return tele.registry


class Scheduler:
    """Continuous-batching front door. `submit()` from any thread;
    results come back as futures resolving to `ServeResult`."""

    def __init__(self, model, params, slots=4, page_size=16,
                 num_pages=None, max_new_cap=None, max_queue=64,
                 admission_window=8, strict_no_retrace=False,
                 prefix_cache=True, prefix_cache_pages=None,
                 draft_model=None, draft_params=None, spec_k=0,
                 slo_ttft=None, shed_policy=None, prefill_chunk=None,
                 kv_dtype=None, host_tier=None, host_tier_pages=None,
                 ladder=None, slots_min=None, slots_max=None,
                 resize_quiet_ticks=32, admission_model=None):
        # -- graftflex: elastic tick geometry -------------------------
        # The ladder is the pow2 set of pre-warmed slot counts the tick
        # may resize between. Explicit `ladder=` wins; otherwise the
        # CLOUD_TPU_SERVE_SLOTS_MIN/_MAX knobs (or ctor args) derive
        # the pow2 rungs in [min, max]; otherwise the geometry is fixed
        # at `slots` (exactly the pre-graftflex engine).
        if slots_min is None:
            env = os.environ.get("CLOUD_TPU_SERVE_SLOTS_MIN",
                                 "").strip()
            slots_min = int(env) if env else None
        if slots_max is None:
            env = os.environ.get("CLOUD_TPU_SERVE_SLOTS_MAX",
                                 "").strip()
            slots_max = int(env) if env else None
        if ladder is None and (slots_min is not None
                               or slots_max is not None):
            lo = int(slots_min if slots_min is not None else 1)
            hi = int(slots_max if slots_max is not None
                     else max(slots, lo))
            if lo < 1 or hi < lo:
                raise ValueError(
                    "need 1 <= slots_min <= slots_max; got min={} "
                    "max={}.".format(lo, hi))
            rungs, w = set(), 1
            while w <= hi:
                if w >= lo:
                    rungs.add(w)
                w *= 2
            ladder = tuple(sorted(rungs | {int(slots)}))
        if num_pages is None:
            # Default: every slot of the WIDEST rung can hold a
            # full-length sequence, plus scratch — paging then bounds
            # fragmentation, not memory, and a grow never needs new
            # pages (the pool serves every geometry).
            widest = max(ladder) if ladder else slots
            num_pages = widest * (model.max_seq_len // page_size) + 1
        # -- graftpack: KV page dtype + host page tier ----------------
        if kv_dtype is None:
            kv_dtype = os.environ.get("CLOUD_TPU_SERVE_KV_DTYPE",
                                      "").strip().lower()
        if kv_dtype in _OFF_VALUES:
            kv_dtype = ""
        if kv_dtype not in ("", "int8"):
            raise ValueError(
                "kv_dtype must be '' or 'int8'; got {!r}.".format(
                    kv_dtype))
        self.kv_dtype = kv_dtype
        if host_tier is None:
            env = os.environ.get("CLOUD_TPU_SERVE_HOST_TIER",
                                 "").strip().lower()
            host_tier = env not in _OFF_VALUES
        if host_tier:
            if draft_model is not None and spec_k > 0:
                raise ValueError(
                    "host_tier is incompatible with speculative decode "
                    "(the verify window transiently writes past the "
                    "committed history a demote key would stamp).")
            if not prefix_cache:
                raise ValueError(
                    "host_tier requires prefix_cache=True (promote "
                    "rides the hit-admission path and registers its "
                    "pages in the trie).")
        self.engine = DecodeEngine(model, params, slots, page_size,
                                   num_pages, max_new_cap=max_new_cap,
                                   draft_model=draft_model,
                                   draft_params=draft_params,
                                   spec_k=spec_k, page_dtype=kv_dtype,
                                   ladder=ladder)
        self.pool = PagePool(num_pages, page_size,
                             self.engine.pages_per_slot,
                             page_dtype=kv_dtype,
                             page_bytes=self.engine.page_hbm_bytes())
        self.host_tier = None
        if host_tier:
            if host_tier_pages is None:
                env = os.environ.get("CLOUD_TPU_SERVE_HOST_TIER_PAGES",
                                     "").strip()
                # Default: 4x the device pool — a host tier exists to
                # be much larger than HBM.
                host_tier_pages = int(env) if env else 4 * num_pages
            self.host_tier = HostPageTier(host_tier_pages, page_size)
        # prefix_cache_pages is the trie's HBM budget (None = half the
        # pool — see PrefixCache); prefix_cache=False disables sharing
        # entirely (every request cold-prefills, the A/B baseline).
        self.trie = (PrefixCache(self.pool, max_pages=prefix_cache_pages)
                     if prefix_cache else None)
        self.strict_no_retrace = bool(strict_no_retrace)
        self._admission_window = int(admission_window)
        self._admit_q = queue.Queue(maxsize=max_queue)
        self._ready = collections.deque()
        self._ready_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._failure = None
        self._slots = [None] * self.engine.slots
        self._free_slots = list(range(self.engine.slots))
        self._started = False
        self._t_start = None
        self._completed = 0
        self._tokens_out = 0
        self._ticks = 0
        self._hits = 0
        self._misses = 0
        self._prefix_tokens_served = 0
        self._accepted_draft_tokens = 0
        self._proposed_draft_tokens = 0
        # Requests admitted but not yet slot-resident. While > 0 and
        # slots are free, the tick loop briefly yields so inserts land
        # before the next tick — a tick advancing 2 of 8 slots costs
        # the same device work as a full one (the batch-synchronous
        # waste this engine exists to avoid).
        self._pending_inserts = 0
        from cloud_tpu.monitoring.telemetry import Histogram
        self._ttft_hist = Histogram("ttft")
        self._ttft_hit_hist = Histogram("ttft_hit")
        self._ttft_miss_hist = Histogram("ttft_miss")
        self._token_hist = Histogram("token_latency")
        self._queue_wait_hist = Histogram("queue_wait")
        self._reserve_wait_hist = Histogram("reserve_wait")
        # Host prefill-latency histogram: always on (like queue wait),
        # because the predicted-TTFT admission model samples its p50
        # even when telemetry export is off.
        self._prefill_hist = Histogram("prefill")
        # graftlens request tracer; installed at start() when
        # CLOUD_TPU_REQTRACE asks for it, else stays None and every
        # rid in the pipeline stays None (zero events, zero file).
        self._trace = None
        self._trace_suppress = False  # warmup traffic is not traced
        # -- graftstorm: SLO-aware admission + chaos state ------------
        if slo_ttft is None:
            env = os.environ.get("CLOUD_TPU_SERVE_SLO_TTFT", "").strip()
            slo_ttft = float(env) if env else None
        self._slo_ttft = slo_ttft
        if shed_policy is None:
            shed_policy = os.environ.get("CLOUD_TPU_SERVE_SHED", "shed")
        shed_policy = str(shed_policy).strip().lower()
        if shed_policy in _OFF_VALUES:
            shed_policy = "off"
        elif shed_policy != "defer":
            shed_policy = "shed"
        self._shed_policy = shed_policy
        self._defer_max = 2
        self._fault_counts = {}
        self._requeues = 0
        self._shed_counts = {}
        self._last_predicted_ttft = None
        self._chaos_lock = threading.Lock()
        self._prefill_fail_armed = 0
        # Squeezed page holds: (pages, release_tick, release_deadline).
        self._squeezed = []
        # -- chunked prefill: budgeted tick interleave ----------------
        if prefill_chunk is None:
            env = os.environ.get("CLOUD_TPU_SERVE_PREFILL_CHUNK",
                                 "").strip().lower()
            prefill_chunk = 0 if env in _OFF_VALUES else int(env)
        prefill_chunk = int(prefill_chunk)
        if prefill_chunk < 0:
            raise ValueError("prefill_chunk must be >= 0 (0 = off); "
                             "got {}.".format(prefill_chunk))
        if prefill_chunk:
            if prefill_chunk & (prefill_chunk - 1):
                raise ValueError(
                    "prefill_chunk must be a power of two (the tail "
                    "bucket family only telescopes then); got "
                    "{}.".format(prefill_chunk))
            if prefill_chunk > model.max_seq_len:
                raise ValueError(
                    "prefill_chunk ({}) exceeds max_seq_len "
                    "({}).".format(prefill_chunk, model.max_seq_len))
        self._prefill_chunk = prefill_chunk or None
        # In-flight ChunkedPrefill continuations, oldest first. Guarded
        # by _ready_lock: the admission thread appends, the tick thread
        # pops/re-queues — at most ONE chunk dispatched per tick.
        self._chunks = collections.deque()
        # How many _pending_inserts are chunk items only THIS loop can
        # advance — excluded from the skip-yield, else the tick loop
        # would sleep waiting on work it alone performs.
        self._chunk_accounted = 0
        self._chunks_dispatched = 0
        self._t_last_commit = None
        # Per-chunk dispatch latency (feeds the chunked admission
        # model) and commit-to-commit decode gap (the p99 the
        # interleave protects; tick COMPUTE time cannot see a loop
        # stalled behind a monolithic prefill).
        self._prefill_chunk_hist = Histogram("prefill_chunk")
        self._decode_gap_hist = Histogram("decode_gap")
        # -- graftflex: resize policy + per-geometry stats ------------
        # Hysteresis: grow fires eagerly (full rung + waiting work at a
        # tick boundary); shrink only after this many consecutive quiet
        # boundaries, so oscillating load never flaps the geometry.
        self._resize_quiet_ticks = int(resize_quiet_ticks)
        if self._resize_quiet_ticks < 1:
            raise ValueError("resize_quiet_ticks must be >= 1; got "
                             "{}.".format(resize_quiet_ticks))
        self._quiet_ticks = 0
        self._resize_counts = {"grow": 0, "shrink": 0}
        self._resize_events = []
        # (new_slots, reason) queued for the tick thread's next
        # boundary — the warmup ladder walk and tests use this hook;
        # the load-adaptive policy calls the same machinery.
        self._requested_resize = None
        # Per-geometry rollups: every per-tick stat stamps the rung it
        # ran under, so A/B comparisons never mix widths silently.
        self._geom_stats = {}
        # -- graftflex: learned admission predictor -------------------
        self._admission_model_path = admission_model
        self._admission_model = None
        self._admission_model_error = None
        self._admission_model_hits = 0

    def _geom(self, slots=None):
        """The per-geometry stats record for `slots` (default: the
        current rung), created on first touch."""
        slots = int(self.engine.slots if slots is None else slots)
        g = self._geom_stats.get(slots)
        if g is None:
            from cloud_tpu.monitoring.telemetry import Histogram
            g = {"ticks": 0, "active_sum": 0,
                 "tick_hist": Histogram("tick_latency_g%d" % slots),
                 "decode_gap_hist": Histogram("decode_gap_g%d" % slots)}
            self._geom_stats[slots] = g
        return g

    # -- lifecycle ----------------------------------------------------

    def start(self):
        if self._started:
            return self
        self._started = True
        self._trace = reqtrace.maybe_enable()
        self._load_admission_model()
        self._t_start = time.monotonic()
        self._prefill_thread = threading.Thread(
            target=self._prefill_loop, name="graftserve-prefill",
            daemon=True)
        self._tick_thread = threading.Thread(
            target=self._tick_loop, name="graftserve-tick", daemon=True)
        self._prefill_thread.start()
        self._tick_thread.start()
        return self

    def close(self):
        """Stops both threads; pending/queued requests fail with a
        RuntimeError (or the loop's typed fault, if one fired)."""
        if not self._started:
            return
        self._stop.set()
        self.pool.close()
        self._wake.set()
        self._prefill_thread.join(timeout=30)
        self._tick_thread.join(timeout=30)
        self._release_squeezes(force=True)
        error = self._failure or RuntimeError("scheduler closed")
        self._fail_pending(error)
        if self._trace is not None:
            self._trace.flush()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    def _load_admission_model(self):
        """Loads the offline-fit admission predictor (ctor arg, else
        `CLOUD_TPU_SERVE_ADMISSION_MODEL`). Absent or unreadable models
        fall back to the live-histogram heuristic — the predictor is an
        accuracy upgrade, never an availability dependency."""
        path = self._admission_model_path
        if path is None:
            path = os.environ.get("CLOUD_TPU_SERVE_ADMISSION_MODEL",
                                  "").strip() or None
        if not path:
            return
        self._admission_model_path = path
        from cloud_tpu.serving import admission
        try:
            self._admission_model = admission.load_model(path)
        except (OSError, ValueError, KeyError) as exc:
            self._admission_model = None
            self._admission_model_error = "{}: {}".format(
                type(exc).__name__, exc)

    # -- graftflex: elastic tick geometry -----------------------------

    @staticmethod
    def resize_decision(ladder, slots, active, waiting, quiet_ticks,
                        quiet_threshold):
        """Pure hysteresis policy, one call per tick boundary. Returns
        `(target_rung_or_None, quiet_ticks')`.

        GROW (eager, the high watermark): the current rung is full AND
        work is waiting — a slammed replica widens instead of shedding,
        immediately. SHRINK (lazy): the active set fits the next rung
        down and nothing waits, for `quiet_threshold` CONSECUTIVE
        boundaries — any burst in between resets the counter, so
        oscillating load holds the wide geometry instead of flapping.
        """
        idx = ladder.index(slots)
        if waiting > 0 and active >= slots and idx + 1 < len(ladder):
            return ladder[idx + 1], 0
        if idx > 0 and waiting == 0 and active <= ladder[idx - 1]:
            quiet_ticks += 1
            if quiet_ticks >= quiet_threshold:
                return ladder[idx - 1], 0
            return None, quiet_ticks
        return None, 0

    def request_resize(self, new_slots, reason="manual", wait=True,
                       timeout=60.0):
        """Queues a resize to ladder rung `new_slots` for the tick
        thread's next boundary (resizes NEVER happen mid-tick). The
        warmup ladder walk and tests drive this; live traffic resizes
        through the same `_resize_to` via the hysteresis policy. With
        `wait`, blocks until the engine reports the new geometry."""
        new_slots = int(new_slots)
        if new_slots not in self.engine.ladder:
            raise ValueError(
                "resize target {} is not a ladder rung {}.".format(
                    new_slots, self.engine.ladder))
        self._requested_resize = (new_slots, reason)
        self._wake.set()
        if not wait:
            return
        deadline = time.monotonic() + timeout
        while self.engine.slots != new_slots:
            if self._failure is not None:
                raise self._failure
            if self._stop.is_set():
                raise RuntimeError("scheduler closed during resize")
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    "resize to {} slots not applied within {}s".format(
                        new_slots, timeout))
            time.sleep(0.002)

    def _maybe_resize(self):
        """Tick-boundary resize hook (tick thread only). Forced
        requests (warmup walk, tests) apply first — retried until the
        occupancy fits; then the hysteresis policy reads live
        occupancy + waiting-work depth. Policy resizes are disabled
        during warmup so the ladder walk owns the geometry."""
        forced = self._requested_resize
        if forced is not None:
            target, reason = forced
            if (target == self.engine.slots
                    or self._resize_to(target, reason)):
                self._requested_resize = None
            return
        if len(self.engine.ladder) <= 1 or self._trace_suppress:
            return
        active = sum(s is not None for s in self._slots)
        # _pending_inserts counts admitted-but-not-resident requests
        # (it decrements at insert), so queue depth + pending is the
        # work a wider tick could be serving right now.
        waiting = self._admit_q.qsize() + self._pending_inserts
        target, self._quiet_ticks = self.resize_decision(
            self.engine.ladder, self.engine.slots, active, waiting,
            self._quiet_ticks, self._resize_quiet_ticks)
        if target is not None:
            self._resize_to(
                target,
                "grow" if target > self.engine.slots else "shrink")

    def _resize_to(self, new_slots, reason):
        """Moves the geometry to `new_slots` one ADJACENT rung at a
        time. Only adjacent (old, new) pairs are pre-warmed by the
        ladder walk — the policy never jumps rungs, so warming the
        O(n^2) pair matrix for the sake of manual/forced jumps would
        buy nothing but compile time. Decomposing keeps every forced
        jump on warmed executables too. Returns False when the live
        set does not fit `new_slots` (the caller retries after
        drains); occupancy cannot change between steps because the
        whole walk runs inside one tick boundary on the tick thread."""
        ladder = self.engine.ladder
        while self.engine.slots != new_slots:
            idx = ladder.index(self.engine.slots)
            step = (ladder[idx + 1] if new_slots > self.engine.slots
                    else ladder[idx - 1])
            if not self._resize_step(step, reason):
                return False
        return True

    def _resize_step(self, new_slots, reason):
        """Applies one resize at the current tick boundary: in-flight
        slots migrate (grow keeps indices; shrink compacts the live
        rows into the low indices), the engine gathers the geometry-
        bound rows under the same perm (bit-identity: rng schedules,
        eos latches, spec state ride along), and the pool is untouched
        — pages never move. Returns False when the live set does not
        fit `new_slots` (the caller retries after drains)."""
        old = self.engine.slots
        occupied = [i for i, s in enumerate(self._slots)
                    if s is not None]
        if len(occupied) > new_slots:
            return False
        if new_slots >= old:
            perm = list(range(old)) + [-1] * (new_slots - old)
        else:
            perm = occupied + [-1] * (new_slots - len(occupied))
        self.engine.resize(new_slots, perm)
        states = self._slots
        self._slots = [states[p] if p >= 0 else None for p in perm]
        self._free_slots = [i for i, s in enumerate(self._slots)
                            if s is None]
        direction = "grow" if new_slots > old else "shrink"
        self._resize_counts[direction] += 1
        self._quiet_ticks = 0
        # Decode gaps never straddle a geometry change — the next
        # commit starts a fresh interval stamped with the new rung.
        self._t_last_commit = None
        event = {"from": old, "to": new_slots, "reason": reason,
                 "tick": self._ticks}
        self._resize_events.append(event)
        trace = self._trace
        if trace is not None and not self._trace_suppress:
            trace.emit(None, "resize", **event)
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.counter(telemetry.SERVE_RESIZES_TOTAL % direction).inc()
            reg.gauge(telemetry.SERVE_SLOT_COUNT).set(new_slots)
        return True

    # -- submission ---------------------------------------------------

    def submit(self, request, timeout=None):
        """Admits one request; returns a Future[ServeResult]. Blocks
        (then raises queue.Full) when the bounded admission queue is
        full — backpressure, by design, reaches the caller."""
        if self._failure is not None:
            raise self._failure
        self._validate(request)
        future = Future()
        t_submit = time.monotonic()
        rid = None
        trace = None if self._trace_suppress else self._trace
        if trace is not None:
            rid = trace.new_request()
            trace.emit(rid, "submitted",
                       prompt_len=len(request.prompt),
                       max_new=int(request.max_new_tokens))
        if request.max_new_tokens == 0:
            future.set_result(ServeResult(
                tokens=np.asarray(request.prompt, np.int32),
                ttft_s=0.0, latency_s=0.0))
            if rid is not None:
                trace.emit(rid, "complete", ttft_s=0.0, latency_s=0.0,
                           tokens=0, prefix_len=0)
            return future
        if request.max_new_tokens > 1:
            self._pending_inserts += 1
        try:
            self._admit_q.put((request, future, t_submit, rid,
                               {"defers": 0}), timeout=timeout)
        except queue.Full:
            if request.max_new_tokens > 1:
                self._pending_inserts -= 1
            if rid is not None:
                trace.emit(rid, "fail", error="queue.Full: admission "
                           "queue full (load shed)")
            raise
        self._observe_queue()
        return future

    def _spec_slack(self):
        return self.engine.spec_k if self.engine.spec_on else 0

    def _validate(self, request):
        model = self.engine.model
        prompt_len = len(request.prompt)
        if prompt_len < 1:
            raise ValueError("prompt must be non-empty.")
        if request.max_new_tokens < 0:
            raise ValueError("max_new_tokens must be >= 0.")
        if prompt_len + request.max_new_tokens > model.max_seq_len:
            raise ValueError(
                "prompt ({}) + max_new_tokens ({}) exceeds max_seq_len "
                "{}.".format(prompt_len, request.max_new_tokens,
                             model.max_seq_len))
        if (self.engine.spec_on and request.max_new_tokens > 1
                and prompt_len + request.max_new_tokens - 1
                + self.engine.spec_k > model.max_seq_len):
            # The verify window transiently writes up to spec_k draft
            # positions past the last committed token.
            raise ValueError(
                "prompt ({}) + max_new_tokens ({}) - 1 + spec_k ({}) "
                "exceeds max_seq_len {} (speculative verify "
                "headroom).".format(prompt_len, request.max_new_tokens,
                                    self.engine.spec_k,
                                    model.max_seq_len))
        if request.max_new_tokens > self.engine.max_new_cap:
            raise ValueError(
                "max_new_tokens ({}) exceeds the engine's max_new_cap "
                "({}).".format(request.max_new_tokens,
                               self.engine.max_new_cap))
        if request.top_k is not None and not (
                1 <= request.top_k <= model.vocab_size):
            raise ValueError("top_k must be in [1, vocab_size={}]; got "
                             "{}.".format(model.vocab_size,
                                          request.top_k))
        if request.top_p is not None and not (
                0.0 < request.top_p <= 1.0):
            raise ValueError("top_p must be in (0, 1]; got {}.".format(
                request.top_p))
        if request.max_new_tokens > 1:
            # Raises when no reservation could EVER satisfy it.
            need = self.pool.pages_needed(prompt_len,
                                          request.max_new_tokens,
                                          slack=self._spec_slack())
            if need > self.pool.capacity:
                raise ValueError(
                    "request needs {} pages; the pool has {} "
                    "allocatable.".format(need, self.pool.capacity))

    def _bucket(self, request):
        from cloud_tpu.models.decoding import bucket_length
        return bucket_length(len(request.prompt),
                             self.engine.max_seq_len)

    def _probe(self, request):
        if self.trie is None or request.max_new_tokens <= 1:
            return 0
        prompt = [int(t) for t in request.prompt]
        matched = self.trie.probe(prompt)
        if self.host_tier is not None:
            # A host-only match must route through the hit path too:
            # the promote executable touches the live cache, which only
            # the tick thread may write.
            matched = max(matched, self.host_tier.probe(prompt))
        return matched

    @staticmethod
    def _sampling(request):
        return {
            "temperature": float(request.temperature),
            "top_k": None if request.top_k is None
            else int(request.top_k),
            "top_p": None if request.top_p is None
            else float(request.top_p),
            "eos_token": None if request.eos_token is None
            else int(request.eos_token),
        }

    # -- admission/prefill thread -------------------------------------

    def _prefill_loop(self):
        runtime.set_phase("serve_prefill")
        while not self._stop.is_set():
            window = self._next_window()
            if not window:
                continue
            # Longest-radix-match-first within the FCFS window, then
            # longest-prefill-first (stable sort: ties stay FCFS). Hits
            # admit cheapest and re-touch their prefix before LRU
            # pressure can evict it; among misses, big prefills hold
            # their slot longest, so starting them earliest minimizes
            # tail latency.
            window.sort(key=lambda item: (-self._probe(item[0]),
                                          -self._bucket(item[0])))
            admitted = 0
            for request, future, t_submit, rid, meta in window:
                if self._stop.is_set():
                    return
                verdict, reason, predicted = self._admission_decision(
                    request, t_submit, admitted, meta)
                if verdict == "defer":
                    meta["defers"] += 1
                    try:
                        self._admit_q.put_nowait(
                            (request, future, t_submit, rid, meta))
                        self._observe_queue()
                        continue
                    except queue.Full:
                        verdict, reason = "shed", "queue_full"
                if verdict == "shed":
                    self._shed(request, future, rid, reason, predicted)
                    continue
                admitted += 1
                try:
                    self._admit_one(request, future, t_submit, rid)
                except BaseException as exc:  # noqa: BLE001
                    if request.max_new_tokens > 1:
                        self._pending_inserts -= 1
                    self._trace_fail(rid, exc)
                    future.set_exception(exc)

    def _next_window(self):
        window = []
        try:
            window.append(self._admit_q.get(timeout=0.05))
        except queue.Empty:
            return window
        while len(window) < self._admission_window:
            try:
                window.append(self._admit_q.get_nowait())
            except queue.Empty:
                break
        # Queue wait ends when the admission thread pops the window:
        # submit -> here is pure queueing, the first segment of the
        # request waterfall and the predicted-TTFT admission input.
        now = time.monotonic()
        reg = _registry()
        trace = self._trace
        for _, _, t_submit, rid, _ in window:
            wait = max(now - t_submit, 0.0)
            self._queue_wait_hist.observe(wait)
            if reg is not None:
                from cloud_tpu.monitoring import telemetry
                reg.histogram(
                    telemetry.SERVE_QUEUE_WAIT_HISTOGRAM).observe(wait)
            if rid is not None and trace is not None:
                trace.emit(rid, "queued", wait_s=wait)
        self._observe_queue()
        return window

    def _reserve_with_pressure(self, need, timeout):
        """One blocking-reserve round; a failed round applies LRU
        eviction pressure to the prefix cache (pages only the trie
        holds are reclaimable) before the caller retries."""
        pages = self.pool.reserve(need, timeout=timeout)
        if pages is None and self.trie is not None:
            self.trie.evict(need)
        return pages

    # -- SLO-aware admission (graftstorm) -----------------------------

    def _predict_ttft(self, request, t_submit, position, now=None):
        """TTFT estimate for a candidate at admission time: queue wait
        already accrued + serialization behind the `position` requests
        admitted ahead of it this window + its own prefill (live p50 of
        the always-on host histogram) + expected page-reservation wait
        (reserve-wait p95) when the pool cannot satisfy it right now.
        All inputs are live histograms, so the estimate tracks the
        current regime instead of a configured constant — unless a
        graftflex admission model is loaded, in which case the offline
        per-phase quantile regressions (fit on the reqtrace corpus's
        exact ground truth) replace the histogram percentiles, with
        the live histograms as fallback for any phase the model cannot
        cover."""
        now = time.monotonic() if now is None else now
        accrued = max(now - t_submit, 0.0)
        model = self._admission_model
        if model is not None:
            pool_short = False
            if request.max_new_tokens > 1:
                need = self.pool.pages_needed(
                    len(request.prompt), request.max_new_tokens,
                    slack=self._spec_slack())
                pool_short = self.pool.available() < need
            predicted = model.predict_ttft(
                accrued=accrued, position=position,
                bucket=self._bucket(request),
                prompt_len=len(request.prompt),
                n_chunks=(self._n_chunks(len(request.prompt))
                          if self._prefill_chunk is not None else None),
                pool_short=pool_short)
            if predicted is not None:
                self._admission_model_hits += 1
                return predicted
        if self._prefill_chunk is not None:
            # Chunk granularity: the candidate costs n_chunks chunk
            # dispatches, interleaved one per tick, and each request
            # admitted ahead of it serializes at least one chunk before
            # the candidate's first. A whole-prefill p50 would be
            # bimodal junk here — short and 4k prompts now differ only
            # in chunk COUNT, not per-dispatch latency.
            chunk_p50 = self._prefill_chunk_hist.percentile(50)
            tick_p50 = self._token_hist.percentile(50)
            n = self._n_chunks(len(request.prompt))
            predicted = (accrued + position * chunk_p50 + n * chunk_p50
                         + max(n - 1, 0) * tick_p50)
        else:
            prefill_p50 = self._prefill_hist.percentile(50)
            predicted = accrued + (position + 1) * prefill_p50
        if request.max_new_tokens > 1:
            need = self.pool.pages_needed(len(request.prompt),
                                          request.max_new_tokens,
                                          slack=self._spec_slack())
            if self.pool.available() < need:
                predicted += self._reserve_wait_hist.percentile(95)
        return predicted

    def _admission_decision(self, request, t_submit, position, meta,
                            now=None):
        """(verdict, reason, predicted_ttft) for one candidate:
        "admit" when the SLO policy is off or the prediction fits,
        "defer" (policy=defer, bounded retries, SLO not yet blown) to
        re-queue behind fresh arrivals, else "shed"."""
        if (self._slo_ttft is None or self._shed_policy == "off"
                or self._trace_suppress):
            return ("admit", None, None)
        now = time.monotonic() if now is None else now
        predicted = self._predict_ttft(request, t_submit, position,
                                       now=now)
        self._last_predicted_ttft = predicted
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.gauge(telemetry.SERVE_PREDICTED_TTFT).set(predicted)
        if predicted <= self._slo_ttft:
            return ("admit", None, predicted)
        accrued = now - t_submit
        if accrued > self._slo_ttft:
            return ("shed", "expired", predicted)
        if (self._shed_policy == "defer"
                and meta.get("defers", 0) < self._defer_max):
            return ("defer", "predicted", predicted)
        reason = "deferred" if meta.get("defers", 0) else "predicted"
        return ("shed", reason, predicted)

    def _shed(self, request, future, rid, reason, predicted):
        """Refuses one candidate by policy: typed ServeShed to the
        caller, `shed` terminal trace event, census counters."""
        if request.max_new_tokens > 1:
            self._pending_inserts -= 1
        self._shed_counts[reason] = self._shed_counts.get(reason, 0) + 1
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.counter(telemetry.SERVE_SHED_TOTAL % reason).inc()
        self._trace_emit(rid, "shed", reason=reason,
                         predicted_ttft=predicted)
        future.set_exception(ServeShed(
            "admission shed ({}): predicted TTFT {:.3f}s > SLO {:.3f}s"
            .format(reason, -1.0 if predicted is None else predicted,
                    self._slo_ttft),
            reason=reason, predicted_ttft=predicted,
            slo_ttft=self._slo_ttft))

    def _admit_one(self, request, future, t_submit, rid=None):
        sampling = self._sampling(request)
        matched = self._probe(request)
        self._trace_emit(rid, "radix_probe", hit=matched > 0,
                         matched_tokens=int(matched))
        if request.max_new_tokens > 1 and matched > 0:
            # Prefix-cache hit: hand the whole admission to the tick
            # thread — the gather-prefill reads the engine's live pool
            # cache, which every tick donates, so no other thread may
            # read it concurrently.
            with self._ready_lock:
                self._ready.append(_HitTicket(request, future, t_submit,
                                              rid=rid))
            self._wake.set()
            return
        if self._prefill_chunk is not None:
            self._admit_miss_chunked(request, future, t_submit, rid,
                                     sampling)
            return
        while True:
            # Re-entered on a transient PrefillFailed: the reservation
            # is released and retaken, so the retry re-queues behind
            # live backpressure instead of squatting on pages.
            pages = []
            if request.max_new_tokens > 1:
                need = self.pool.pages_needed(len(request.prompt),
                                              request.max_new_tokens,
                                              slack=self._spec_slack())
                pages = None
                t_reserve0 = time.monotonic()
                while not self._stop.is_set():
                    pages = self._reserve_with_pressure(need,
                                                        timeout=0.2)
                    if pages is not None:
                        break
                if pages is None:  # shutdown while blocked on the pool
                    self._pending_inserts -= 1
                    error = RuntimeError("scheduler closed")
                    self._trace_fail(rid, error)
                    future.set_exception(error)
                    return
                wait = time.monotonic() - t_reserve0
                self._observe_reserve_wait(wait)
                self._trace_emit(rid, "pages_reserved",
                                 pages=len(pages), wait_s=wait)
            t_prefill0 = time.monotonic()
            try:
                result = self._engine_prefill(
                    np.asarray(request.prompt, np.int32),
                    request.max_new_tokens,
                    jax.random.PRNGKey(request.rng_seed), sampling)
            except PrefillFailed as exc:
                if pages:
                    self.pool.free(pages)
                self._note_fault(exc, rid=rid, slot=None)
                self._note_requeue(rid, tokens_done=0)
                continue
            except BaseException:
                if pages:
                    self.pool.free(pages)
                raise
            break
        ttft = time.monotonic() - t_submit
        self._record_ttft(ttft, hit=False)
        self._observe_prefill(time.monotonic() - t_prefill0)
        self._trace_emit(rid, "prefill", bucket=int(result.bucket),
                         prefix_len=0,
                         dur_s=time.monotonic() - t_prefill0)
        if request.max_new_tokens == 1:
            # Completes at prefill: no slot, no pages, no tick.
            self.engine.release_prefill(result)
            self._complete(request, future, t_submit, ttft,
                           [result.first_token], prefix_len=0, rid=rid)
            return
        with self._ready_lock:
            self._ready.append(_ReadyItem(request, result, pages,
                                          future, t_submit, ttft,
                                          rid=rid))
        self._wake.set()

    def _record_ttft(self, ttft, hit):
        self._ttft_hist.observe(ttft)
        (self._ttft_hit_hist if hit else self._ttft_miss_hist).observe(
            ttft)
        if hit:
            self._hits += 1
        else:
            self._misses += 1
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.histogram(telemetry.SERVE_TTFT_HISTOGRAM).observe(ttft)
            name = (telemetry.SERVE_TTFT_HIT_HISTOGRAM if hit
                    else telemetry.SERVE_TTFT_MISS_HISTOGRAM)
            reg.histogram(name).observe(ttft)
            total = self._hits + self._misses
            reg.gauge(telemetry.SERVE_PREFIX_HIT_RATE).set(
                self._hits / total if total else 0.0)

    # -- chunked prefill: tick-interleaved continuations --------------

    def _n_chunks(self, n_suffix):
        """Chunk count for an `n_suffix`-token prefill at the
        configured chunk size (1 when chunking is off)."""
        if self._prefill_chunk is None or n_suffix <= 0:
            return 1
        return (n_suffix - 1) // self._prefill_chunk + 1

    def _admit_miss_chunked(self, request, future, t_submit, rid,
                            sampling):
        """Miss admission with chunking on: reserve pages here (same
        blocking backpressure as the whole-prefill path), then hand the
        request to the tick thread as a ChunkedPrefill continuation —
        the admission thread never touches the device, so a long
        prompt cannot monopolize the chip between ticks. Chaos
        `prefill_fail` moves to chunk dispatch."""
        pages = []
        if request.max_new_tokens > 1:
            need = self.pool.pages_needed(len(request.prompt),
                                          request.max_new_tokens,
                                          slack=self._spec_slack())
            pages = None
            t_reserve0 = time.monotonic()
            while not self._stop.is_set():
                pages = self._reserve_with_pressure(need, timeout=0.2)
                if pages is not None:
                    break
            if pages is None:  # shutdown while blocked on the pool
                self._pending_inserts -= 1
                error = RuntimeError("scheduler closed")
                self._trace_fail(rid, error)
                future.set_exception(error)
                return
            wait = time.monotonic() - t_reserve0
            self._observe_reserve_wait(wait)
            self._trace_emit(rid, "pages_reserved", pages=len(pages),
                             wait_s=wait)
        chunked = self.engine.prefill_chunks(
            np.asarray(request.prompt, np.int32),
            request.max_new_tokens, jax.random.PRNGKey(request.rng_seed),
            sampling, self._prefill_chunk)
        self._enqueue_chunk_item(_ChunkItem(
            "miss", request, chunked, future, t_submit, rid=rid,
            pages=pages))

    def _enqueue_chunk_item(self, item):
        self.pool.note_prefill_hold(len(item.all_pages()))
        with self._ready_lock:
            if item.counts_pending:
                self._chunk_accounted += 1
            self._chunks.append(item)
        self._wake.set()

    def _release_chunk_hold(self, item):
        if not item.hold_released:
            item.hold_released = True
            self.pool.note_prefill_release(len(item.all_pages()))

    def _fail_chunk_item(self, item, error):
        """Drains one chunk item on failure/shutdown: caches park,
        pages free (exactly once), the future fails, and the pending-
        insert accounting unwinds."""
        try:
            item.chunked.abandon()
        except Exception:  # noqa: BLE001 — drain is best-effort
            pass
        if item.result is not None:
            try:
                self.engine.release_prefill(item.result)
            except Exception:  # noqa: BLE001
                pass
            item.result = None
        self._release_chunk_hold(item)
        pages = item.all_pages()
        if pages:
            self.pool.free(pages)
        with self._ready_lock:
            if item.counts_pending:
                self._chunk_accounted -= 1
        if item.counts_pending:
            self._pending_inserts -= 1
        if not item.future.done():
            self._trace_fail(item.rid, error)
            item.future.set_exception(error)

    def _step_chunks(self):
        """Budgeted interleave: dispatch at most ONE prefill chunk per
        tick-loop iteration, oldest continuation first. Chaos
        `prefill_fail` is consumed at the chunk boundary — the faulted
        dispatch counts a fault + requeue but the continuation keeps
        its already-computed chunks (retained progress; the retry costs
        one tick, not a re-prefill). The tail chunk records TTFT and
        moves the item to the ready deque for slot insertion (or
        completes outright when max_new == 1). Returns True when a
        chunk was dispatched so the idle branch can drain continuations
        back-to-back instead of sleeping."""
        with self._ready_lock:
            if not self._chunks:
                return False
            item = self._chunks.popleft()
        if self._stop.is_set():
            self._fail_chunk_item(
                item, self._failure or RuntimeError("scheduler closed"))
            return False
        with self._chaos_lock:
            armed = self._prefill_fail_armed > 0
            if armed:
                self._prefill_fail_armed -= 1
        if armed:
            self._note_fault(
                PrefillFailed("graftchaos: injected prefill_fail"),
                rid=item.rid, slot=None)
            self._note_requeue(item.rid, tokens_done=0)
            with self._ready_lock:
                self._chunks.appendleft(item)
            return True
        if item.t_prefill0 is None:
            item.t_prefill0 = time.monotonic()
        i = item.chunked.chunks_done
        t0 = time.monotonic()
        try:
            result = item.chunked.step()
        except BaseException as exc:  # noqa: BLE001
            self._fail_chunk_item(item, exc)
            raise
        dur = time.monotonic() - t0
        self._chunks_dispatched += 1
        self._observe_prefill_chunk(dur)
        self._trace_emit(item.rid, "prefill_chunk", i=int(i),
                         n=int(item.chunked.n_chunks),
                         tokens=int(item.chunked.chunk_tokens(i)),
                         dur_s=dur)
        if result is None:
            with self._ready_lock:
                self._chunks.appendleft(item)
            return True
        item.result = result
        now = time.monotonic()
        if item.kind != "requeue":
            item.ttft_s = now - item.t_submit
            self._record_ttft(item.ttft_s, hit=item.kind == "hit")
        self._observe_prefill(now - item.t_prefill0)
        self._trace_emit(item.rid, "prefill", bucket=int(result.bucket),
                         prefix_len=int(item.prefix_len),
                         dur_s=now - item.t_prefill0,
                         chunks=int(item.chunked.n_chunks))
        if item.kind == "hit":
            self._prefix_tokens_served += item.prefix_len
        if item.request.max_new_tokens == 1:
            # Completes at prefill: no slot, no pages, no tick.
            self.engine.release_prefill(result)
            item.result = None
            self._release_chunk_hold(item)
            self._complete(item.request, item.future, item.t_submit,
                           item.ttft_s, [result.first_token],
                           prefix_len=item.result_prefix_len,
                           rid=item.rid)
            return True
        with self._ready_lock:
            self._ready.append(item)
        return True

    def _insert_chunk_item(self, item):
        """Slot insertion for a completed chunked prefill (the tail
        chunk already ran): the kind-specific page-vector split and
        bookkeeping of the three unchunked insert paths, unified."""
        if self._stop.is_set():
            self._fail_chunk_item(
                item, self._failure or RuntimeError("scheduler closed"))
            return
        held = item.pages_held()
        slot = self._free_slots.pop()
        state = _Slot(item.request, held, item.future, item.t_submit,
                      item.ttft_s, prefix_len=item.prefix_len,
                      rid=item.rid)
        state.result_prefix_len = item.result_prefix_len
        state.emitted.append(item.result.first_token)
        state.step_keys = item.result.step_keys
        self._slots[slot] = state
        page_vec = self.pool.page_vec(held)
        if item.kind == "hit":
            # Shared pages are immutable: route their scatter entries
            # to scratch, reconstruct divergence into fresh pages.
            scatter_vec = self.pool.page_vec(
                [0] * len(item.shared) + list(item.fresh))
        else:
            scatter_vec = page_vec
        self.engine.insert(slot, item.result, page_vec, scatter_vec,
                           self._sampling(item.request))
        item.result = None
        self._trace_emit(item.rid, "slot_insert", slot=slot)
        if item.kind == "hit" and item.partial_len:
            # The divergent page was reconstructed into a fresh page by
            # the insert scatter — device-side copy-on-write done.
            self.pool.note_cow()
            self.pool.free([item.partial_page])
        self._release_chunk_hold(item)
        if item.kind != "requeue":
            self._register(item.request, held)
        if item.counts_pending:
            self._pending_inserts -= 1
            with self._ready_lock:
                self._chunk_accounted -= 1
        self._observe_gauges()

    def _observe_prefill_chunk(self, dur):
        self._prefill_chunk_hist.observe(dur)
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.histogram(
                telemetry.SERVE_PREFILL_CHUNK_HISTOGRAM).observe(dur)
            reg.counter(telemetry.SERVE_PREFILL_CHUNKS_TOTAL).inc()

    def _observe_decode_gap(self, gap, n_active):
        if n_active <= 0:
            return
        self._decode_gap_hist.observe(gap, count=n_active)
        # Geometry stamp: the same gap also lands in the current
        # rung's histogram, so A/B reads never mix widths silently.
        self._geom()["decode_gap_hist"].observe(gap, count=n_active)
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.histogram(
                telemetry.SERVE_DECODE_GAP_HISTOGRAM).observe(
                    gap, count=n_active)

    # -- graftstorm: chaos + slot fault recovery ----------------------

    def _engine_prefill(self, *args, **kwargs):
        """Every prefill dispatch funnels here so an armed chaos
        `prefill_fail` hits whichever thread prefills next (admission
        thread for misses, tick thread for hits/requeues)."""
        with self._chaos_lock:
            armed = self._prefill_fail_armed > 0
            if armed:
                self._prefill_fail_armed -= 1
        if armed:
            raise PrefillFailed("graftchaos: injected prefill_fail")
        return self.engine.prefill(*args, **kwargs)

    def _chaos_pre_tick(self):
        """Tick-loop chaos hook: returns squeezed pages whose hold
        expired, then consumes due serving injections. Warm-up traffic
        is exempt (the tick counter resets after warmup, so configured
        ticks index post-warmup traffic only)."""
        self._release_squeezes()
        if self._trace_suppress:
            return
        from cloud_tpu.analysis import chaos
        plan = chaos.active_plan()
        if plan is None:
            return
        for event in plan.pre_tick(self._ticks):
            self._apply_chaos(event)

    def _apply_chaos(self, event):
        if event.kind == "prefill_fail":
            with self._chaos_lock:
                self._prefill_fail_armed += 1
            return
        if event.kind == "pool_squeeze":
            n = 1 if event.arg is None else int(event.arg)
            pages = self.pool.squeeze(n)
            self._note_fault(PoolSqueezed(
                "graftchaos: squeezed {} page(s) at tick {}".format(
                    len(pages), self._ticks)))
            if pages:
                self._squeezed.append(
                    (pages, self._ticks + SQUEEZE_HOLD_TICKS,
                     time.monotonic() + SQUEEZE_HOLD_S))
            return
        victim = None
        if event.kind == "slot_evict" and event.arg is not None:
            idx = int(event.arg)
            if 0 <= idx < len(self._slots) and \
                    self._slots[idx] is not None:
                victim = idx
        else:
            for idx, state in enumerate(self._slots):
                if state is not None:
                    victim = idx
                    break
        if victim is None:
            # Nothing in flight to fault — the one-shot still fired
            # (logged by the plan), the injection is a no-op.
            return
        cls = SlotHang if event.kind == "slot_hang" else SlotEvicted
        self._fault_slot(victim, self._slots[victim], cls(
            "graftchaos: {} slot {} at tick {}".format(
                event.kind, victim, self._ticks)))

    def _release_squeezes(self, force=False):
        if not self._squeezed:
            return
        now = time.monotonic()
        keep = []
        for pages, release_tick, deadline in self._squeezed:
            if force or self._ticks >= release_tick or now >= deadline:
                self.pool.free(pages)
            else:
                keep.append((pages, release_tick, deadline))
        self._squeezed = keep

    def _fault_slot(self, slot, state, fault):
        """Slot-level fault recovery: drain the victim through the
        SAME fixed-shape evict scatter finished slots use (the
        persistent tick never stops), return its pages exactly once
        (prefix-trie references survive untouched), and requeue its
        request with retained progress."""
        self._note_fault(fault, rid=state.rid, slot=slot)
        evict_mask = np.zeros((self.engine.slots,), bool)
        evict_mask[slot] = True
        self.engine.evict(evict_mask)
        self._slots[slot] = None
        self._free_slots.append(slot)
        self.pool.free(state.pages)
        self._requeue_slot(state)
        self._observe_gauges()

    def _requeue_slot(self, state):
        """Builds the typed continuation: original prompt + emitted
        tokens become the new prompt, max_new shrinks by the same
        count, and the ORIGINAL schedule rows n-1 / n.. ride along as
        the engine's key_override — so the continuation's first token
        samples with exactly the key the uninterrupted run would have
        consumed (bit-identity). Front of the ready deque: a faulted
        request has already waited once."""
        request = state.request
        emitted = [int(t) for t in state.emitted]
        n = len(emitted)
        eos = request.eos_token
        if eos is not None and eos in emitted:
            # eos already latched: the remaining decode is pure eos
            # replay, which _complete's fill reproduces on host.
            done = emitted[:emitted.index(eos) + 1]
            self._complete(request, state.future, state.t_submit,
                           state.ttft_s, done,
                           prefix_len=state.result_prefix_len,
                           rid=state.rid)
            return
        if n >= request.max_new_tokens:
            self._complete(request, state.future, state.t_submit,
                           state.ttft_s, emitted,
                           prefix_len=state.result_prefix_len,
                           rid=state.rid)
            return
        self._note_requeue(state.rid, tokens_done=n)
        cont = dataclasses.replace(
            request,
            prompt=[int(t) for t in request.prompt] + emitted,
            max_new_tokens=request.max_new_tokens - n)
        item = _RequeueItem(
            cont, np.array(state.step_keys[n - 1], np.uint32),
            np.array(state.step_keys[n:], np.uint32),
            state.future, state.t_submit, state.ttft_s,
            state.result_prefix_len, rid=state.rid)
        with self._ready_lock:
            self._ready.appendleft(item)
        self._wake.set()

    def _note_fault(self, fault, rid=None, slot=None):
        kind = fault_kind(fault)
        self._fault_counts[kind] = self._fault_counts.get(kind, 0) + 1
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.counter(telemetry.SERVE_FAULTS_TOTAL % kind).inc()
        if rid is not None:
            self._trace_emit(rid, "slot_fault", kind=kind, slot=slot)

    def _note_requeue(self, rid, tokens_done):
        self._requeues += 1
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.counter(telemetry.SERVE_REQUEUES_TOTAL).inc()
        self._trace_emit(rid, "requeue", tokens_done=int(tokens_done))

    def _observe_prefill(self, dur):
        self._prefill_hist.observe(dur)
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.histogram(
                telemetry.SERVE_PREFILL_HISTOGRAM).observe(dur)

    # -- tick thread --------------------------------------------------

    def _tick_loop(self):
        runtime.set_phase("serve_tick")
        from cloud_tpu.monitoring import watch
        # Adopt an installed graftwatch: the tick thread becomes the
        # beat source AND the async-raise target, so a stuck tick is
        # the thread the stall fault interrupts (typed
        # BackendUnavailable + blackbox), not a silent hang.
        watch.rewatch()
        skips = 0
        try:
            while not self._stop.is_set():
                if watch.enabled():
                    watch.heartbeat()
                    watch.check()
                self._chaos_pre_tick()
                # Tick boundary: the only point the geometry may move —
                # never mid-tick, never from another thread.
                self._maybe_resize()
                stepped = self._step_chunks()
                self._insert_ready()
                if not any(s is not None for s in self._slots):
                    self._t_last_commit = None
                    if stepped:
                        # A continuation advanced and nothing decodes:
                        # drain chunks back-to-back, no idle sleep.
                        continue
                    if not self._wake.wait(timeout=0.05):
                        continue
                    self._wake.clear()
                    continue
                if (self._free_slots
                        # A stale read only mis-times one 5 ms pacing
                        # nap; correctness never depends on it.
                        and self._pending_inserts > self._chunk_accounted  # graftlint: unlocked-ok
                        and skips < 40):
                    # Admissions are in flight on OTHER threads and
                    # slots are open: yield briefly so the insert lands
                    # before the next tick. The skip cap bounds the
                    # stall when an admission is itself blocked on
                    # pages only ticks can free. In-flight chunked
                    # prefills are excluded — only this loop advances
                    # them, so waiting on them would stall every
                    # resident slot for nothing.
                    skips += 1
                    self._wake.wait(timeout=0.005)
                    self._wake.clear()
                    continue
                skips = 0
                t0 = time.monotonic()
                out = self.engine.tick()
                fetched = runtime.device_fetch(out)
                t_commit = time.monotonic()
                elapsed = t_commit - t0
                # monotonic() and monotonic_ns() share an epoch, so the
                # span timestamps line up with the tracer's records.
                spans.complete("serve_tick", int(t0 * 1e9),
                               int(elapsed * 1e9))
                self._ticks += 1
                if self._t_last_commit is not None:
                    self._observe_decode_gap(
                        t_commit - self._t_last_commit,
                        sum(s is not None for s in self._slots))
                self._t_last_commit = t_commit
                self._distribute(fetched, elapsed)
                if self.strict_no_retrace:
                    self.engine.check_no_retrace()
        except BaseException as exc:  # noqa: BLE001
            self._failure = exc
            self._stop.set()
            self.pool.close()
            self._fail_pending(exc)

    def _insert_ready(self):
        # Hit tickets blocked on page reservation are stashed and
        # restored at the front afterwards: a page-starved hit must not
        # head-of-line-block ready misses (whose pages are already
        # reserved — inserting them is what eventually frees pages).
        blocked = []
        try:
            while self._free_slots:
                with self._ready_lock:
                    if not self._ready:
                        return
                    item = self._ready.popleft()
                if isinstance(item, _HitTicket):
                    if not self._admit_hit(item):
                        blocked.append(item)
                    continue
                if isinstance(item, _RequeueItem):
                    if not self._insert_requeue(item):
                        blocked.append(item)
                    continue
                if isinstance(item, _ChunkItem):
                    self._insert_chunk_item(item)
                    continue
                self._insert_miss_item(item)
        finally:
            if blocked:
                with self._ready_lock:
                    self._ready.extendleft(reversed(blocked))

    def _insert_miss_item(self, item):
        slot = self._free_slots.pop()
        state = _Slot(item.request, item.pages, item.future,
                      item.t_submit, item.ttft_s, prefix_len=0,
                      rid=item.rid)
        state.emitted.append(item.result.first_token)
        state.step_keys = item.result.step_keys
        self._slots[slot] = state
        vec = self.pool.page_vec(item.pages)
        self.engine.insert(slot, item.result, vec, vec,
                           self._sampling(item.request))
        self._trace_emit(item.rid, "slot_insert", slot=slot)
        self._register(item.request, item.pages)
        self._pending_inserts -= 1
        self._observe_gauges()

    def _insert_requeue(self, item):
        """Tick-thread re-admission of a faulted request's continuation:
        reserve (non-blocking — a starved requeue stays queued), cold
        re-prefill under the key_override schedule, insert. No new TTFT
        observation — the request's TTFT happened at its ORIGINAL
        prefill and is carried through. Returns False when pages are
        not available yet."""
        request = item.request
        if self._stop.is_set():
            if not item.future.done():
                error = (self._failure
                         or RuntimeError("scheduler closed"))
                self._trace_fail(item.rid, error)
                item.future.set_exception(error)
            return True
        key_override = (item.key, item.rest)
        if self._prefill_chunk is not None:
            pages = []
            if request.max_new_tokens > 1:
                need = self.pool.pages_needed(len(request.prompt),
                                              request.max_new_tokens,
                                              slack=self._spec_slack())
                pages = self._reserve_with_pressure(need, timeout=0.01)
                if pages is None:
                    return False
                self._trace_emit(item.rid, "pages_reserved",
                                 pages=len(pages), wait_s=0.0)
            chunked = self.engine.prefill_chunks(
                np.asarray(request.prompt, np.int32),
                request.max_new_tokens,
                jax.random.PRNGKey(request.rng_seed),
                self._sampling(request), self._prefill_chunk,
                key_override=key_override)
            self._enqueue_chunk_item(_ChunkItem(
                "requeue", request, chunked, item.future,
                item.t_submit, rid=item.rid, pages=pages,
                result_prefix_len=item.result_prefix_len,
                ttft_s=item.ttft_s))
            return True
        if request.max_new_tokens == 1:
            # Single remaining token: completes at prefill, no slot.
            try:
                result = self._engine_prefill(
                    np.asarray(request.prompt, np.int32), 1,
                    jax.random.PRNGKey(request.rng_seed),
                    self._sampling(request),
                    key_override=key_override)
            except PrefillFailed as exc:
                self._note_fault(exc, rid=item.rid, slot=None)
                return False
            self.engine.release_prefill(result)
            self._complete(request, item.future, item.t_submit,
                           item.ttft_s, [result.first_token],
                           prefix_len=item.result_prefix_len,
                           rid=item.rid)
            return True
        need = self.pool.pages_needed(len(request.prompt),
                                      request.max_new_tokens,
                                      slack=self._spec_slack())
        pages = self._reserve_with_pressure(need, timeout=0.01)
        if pages is None:
            return False
        self._trace_emit(item.rid, "pages_reserved", pages=len(pages),
                         wait_s=0.0)
        t_prefill0 = time.monotonic()
        try:
            result = self._engine_prefill(
                np.asarray(request.prompt, np.int32),
                request.max_new_tokens,
                jax.random.PRNGKey(request.rng_seed),
                self._sampling(request), key_override=key_override)
        except PrefillFailed as exc:
            self.pool.free(pages)
            self._note_fault(exc, rid=item.rid, slot=None)
            return False
        except BaseException:
            self.pool.free(pages)
            raise
        self._observe_prefill(time.monotonic() - t_prefill0)
        self._trace_emit(item.rid, "prefill",
                         bucket=int(result.bucket), prefix_len=0,
                         dur_s=time.monotonic() - t_prefill0)
        slot = self._free_slots.pop()
        state = _Slot(request, pages, item.future, item.t_submit,
                      item.ttft_s, prefix_len=0, rid=item.rid)
        state.result_prefix_len = item.result_prefix_len
        state.emitted.append(result.first_token)
        state.step_keys = result.step_keys
        self._slots[slot] = state
        vec = self.pool.page_vec(pages)
        self.engine.insert(slot, result, vec, vec,
                           self._sampling(request))
        self._trace_emit(item.rid, "slot_insert", slot=slot)
        self._observe_gauges()
        return True

    def _admit_hit(self, ticket):
        """Tick-thread admission of a prefix-cache hit: match (taking
        pool refs), trim the match until the padded suffix fits the
        cache, reserve fresh pages for the unshared tail, gather-prefill
        the suffix, insert, register. Returns False (nothing consumed)
        when fresh pages cannot be reserved yet."""
        from cloud_tpu.models.decoding import bucket_length

        request = ticket.request
        if self._stop.is_set():
            self._pending_inserts -= 1
            if not ticket.future.done():
                error = (self._failure
                         or RuntimeError("scheduler closed"))
                self._trace_fail(ticket.rid, error)
                ticket.future.set_exception(error)
            return True
        prompt = [int(t) for t in request.prompt]
        prompt_len = len(prompt)
        page = self.pool.page_size
        total = self.pool.pages_needed(prompt_len,
                                       request.max_new_tokens,
                                       slack=self._spec_slack())
        match = self.trie.match(prompt)
        shared = list(match.pages)
        partial_page = match.partial_page
        partial_len = match.partial_len
        prefix_len = match.prefix_len
        # Trim until prefix + pow2(suffix) fits max_seq_len: drop the
        # partial first, then whole pages (each dropped page's ref goes
        # straight back).
        while prefix_len and (prefix_len + bucket_length(
                prompt_len - prefix_len, self.engine.max_seq_len)
                > self.engine.max_seq_len):
            if partial_len:
                self.pool.free([partial_page])
                partial_page, partial_len = None, 0
            else:
                self.pool.free([shared.pop()])
            prefix_len = len(shared) * page + partial_len
        shared, partial_page, partial_len, prefix_len = \
            self._host_extend(ticket, prompt, prompt_len, shared,
                              partial_page, partial_len, prefix_len)
        held = shared + ([partial_page] if partial_len else [])
        if prefix_len == 0:
            # Evicted (or trimmed away) between probe and match: it is
            # a plain miss now — run it here; the tick thread is also
            # allowed to prefill.
            if held:
                self.pool.free(held)
            return self._admit_miss_on_tick(ticket, total)
        if ticket.t_reserve0 is None:
            ticket.t_reserve0 = time.monotonic()
        fresh = self._reserve_with_pressure(total - len(shared),
                                            timeout=0.01)
        if fresh is None:
            self.pool.free(held)
            return False
        wait = time.monotonic() - ticket.t_reserve0
        self._observe_reserve_wait(wait)
        self._trace_emit(ticket.rid, "pages_reserved",
                         pages=len(fresh), wait_s=wait)
        if self._prefill_chunk is not None:
            # The gather runs lazily at the first chunk step (tick
            # thread — safe); the held refs keep the prefix pages'
            # content live until then.
            chunked = self.engine.prefill_chunks(
                np.asarray(prompt, np.int32), request.max_new_tokens,
                jax.random.PRNGKey(request.rng_seed),
                self._sampling(request), self._prefill_chunk,
                prefix_len=prefix_len,
                gather_vec=self.pool.page_vec(held))
            self._enqueue_chunk_item(_ChunkItem(
                "hit", request, chunked, ticket.future,
                ticket.t_submit, rid=ticket.rid, shared=shared,
                fresh=fresh, partial_page=partial_page,
                partial_len=partial_len, prefix_len=prefix_len,
                result_prefix_len=prefix_len))
            return True
        t_prefill0 = time.monotonic()
        try:
            result = self._engine_prefill(
                np.asarray(prompt, np.int32), request.max_new_tokens,
                jax.random.PRNGKey(request.rng_seed),
                self._sampling(request), prefix_len=prefix_len,
                gather_vec=self.pool.page_vec(held))
        except PrefillFailed as exc:
            self.pool.free(held + fresh)
            self._note_fault(exc, rid=ticket.rid, slot=None)
            self._note_requeue(ticket.rid, tokens_done=0)
            return False
        except BaseException:
            self.pool.free(held + fresh)
            raise
        ttft = time.monotonic() - ticket.t_submit
        self._record_ttft(ttft, hit=True)
        self._observe_prefill(time.monotonic() - t_prefill0)
        self._trace_emit(ticket.rid, "prefill",
                         bucket=int(result.bucket),
                         prefix_len=int(prefix_len),
                         dur_s=time.monotonic() - t_prefill0)
        self._prefix_tokens_served += prefix_len
        slot = self._free_slots.pop()
        state = _Slot(request, shared + fresh, ticket.future,
                      ticket.t_submit, ttft, prefix_len=prefix_len,
                      rid=ticket.rid)
        state.emitted.append(result.first_token)
        state.step_keys = result.step_keys
        self._slots[slot] = state
        page_vec = self.pool.page_vec(shared + fresh)
        scatter_vec = self.pool.page_vec([0] * len(shared) + fresh)
        self.engine.insert(slot, result, page_vec, scatter_vec,
                           self._sampling(request))
        self._trace_emit(ticket.rid, "slot_insert", slot=slot)
        if partial_len:
            # The divergent page was reconstructed into its fresh page
            # by the insert scatter — the device-side copy-on-write.
            self.pool.note_cow()
            self.pool.free([partial_page])
        self._register(request, shared + fresh)
        self._pending_inserts -= 1
        self._observe_gauges()
        return True

    def _admit_miss_on_tick(self, ticket, need):
        """Fallback when a probed hit vanished before `match`: admit it
        as a miss without bouncing back to the admission thread."""
        request = ticket.request
        if ticket.t_reserve0 is None:
            ticket.t_reserve0 = time.monotonic()
        pages = self._reserve_with_pressure(need, timeout=0.01)
        if pages is None:
            return False
        wait = time.monotonic() - ticket.t_reserve0
        self._observe_reserve_wait(wait)
        self._trace_emit(ticket.rid, "pages_reserved",
                         pages=len(pages), wait_s=wait)
        if self._prefill_chunk is not None:
            chunked = self.engine.prefill_chunks(
                np.asarray(request.prompt, np.int32),
                request.max_new_tokens,
                jax.random.PRNGKey(request.rng_seed),
                self._sampling(request), self._prefill_chunk)
            self._enqueue_chunk_item(_ChunkItem(
                "miss", request, chunked, ticket.future,
                ticket.t_submit, rid=ticket.rid, pages=pages))
            return True
        t_prefill0 = time.monotonic()
        try:
            result = self._engine_prefill(
                np.asarray(request.prompt, np.int32),
                request.max_new_tokens,
                jax.random.PRNGKey(request.rng_seed),
                self._sampling(request))
        except PrefillFailed as exc:
            self.pool.free(pages)
            self._note_fault(exc, rid=ticket.rid, slot=None)
            self._note_requeue(ticket.rid, tokens_done=0)
            return False
        except BaseException:
            self.pool.free(pages)
            raise
        ttft = time.monotonic() - ticket.t_submit
        self._record_ttft(ttft, hit=False)
        self._observe_prefill(time.monotonic() - t_prefill0)
        self._trace_emit(ticket.rid, "prefill",
                         bucket=int(result.bucket), prefix_len=0,
                         dur_s=time.monotonic() - t_prefill0)
        slot = self._free_slots.pop()
        state = _Slot(request, pages, ticket.future, ticket.t_submit,
                      ttft, prefix_len=0, rid=ticket.rid)
        state.emitted.append(result.first_token)
        state.step_keys = result.step_keys
        self._slots[slot] = state
        vec = self.pool.page_vec(pages)
        self.engine.insert(slot, result, vec, vec,
                           self._sampling(request))
        self._trace_emit(ticket.rid, "slot_insert", slot=slot)
        self._register(request, pages)
        self._pending_inserts -= 1
        self._observe_gauges()
        return True

    def _register(self, request, pages):
        """Indexes the inserted request's full prompt pages (tick
        thread, right after insert: the pages are populated and
        immutable from here — decode writes start past the prompt)."""
        if self.trie is None or request.max_new_tokens <= 1:
            return
        self.trie.register([int(t) for t in request.prompt], pages)

    # -- graftpack: host page tier demote/promote ---------------------

    def _host_extend(self, ticket, prompt, prompt_len, shared,
                     partial_page, partial_len, prefix_len):
        """Promote: extend the trie's device-resident prefix with
        host-tier pages from a completed earlier turn. Finds the
        longest host entry strictly past the trie match (page-aligned,
        leaving >= 1 suffix token, and fitting the same
        prefix+pow2(suffix) constraint the trim loop enforces),
        verifies its tree_digest (mismatch -> typed HostTierCorrupt,
        entry dropped, the trie prefix alone carries on — corrupt
        pages are never mapped), reserves the extension pages
        NON-BLOCKING (promotion is an optimization; a starved pool
        falls back to re-prefilling the tail), and runs the engine's
        fixed-shape promote scatter. The extension pages ride the hit
        flow as extra `shared` pages: the insert scatter routes them
        to scratch, `_register` indexes them, refcounts balance
        exactly like trie-matched pages. Tick thread only."""
        tier = self.host_tier
        if tier is None:
            return shared, partial_page, partial_len, prefix_len
        from cloud_tpu.models.decoding import bucket_length
        from cloud_tpu.training.checkpoint import tree_digest
        page = self.pool.page_size
        n_t = len(shared)
        n_h = 0
        for n in range((prompt_len - 1) // page, n_t, -1):
            if (n * page + bucket_length(prompt_len - n * page,
                                         self.engine.max_seq_len)
                    > self.engine.max_seq_len):
                continue
            if tier.contains(prompt[:n * page]):
                n_h = n
                break
        if n_h == 0:
            return shared, partial_page, partial_len, prefix_len
        entry = tier.get(prompt, n_h)
        if entry is None:  # concurrently evicted between probe and get
            return shared, partial_page, partial_len, prefix_len
        if tree_digest(entry["pages"]) != entry["digest"]:
            tier.note_digest_failure()
            tier.drop(prompt, n_h)
            self._note_fault(HostTierCorrupt(
                "host-tier digest mismatch at {} pages; entry dropped, "
                "falling back to re-prefill.".format(n_h)),
                rid=ticket.rid, slot=None)
            reg = _registry()
            if reg is not None:
                from cloud_tpu.monitoring import telemetry
                reg.counter(telemetry.SERVE_DIGEST_FAILURES_TOTAL).inc()
            return shared, partial_page, partial_len, prefix_len
        # Plain non-blocking reserve — no trie eviction pressure; a
        # promote must never evict device-resident prefixes to make
        # room for itself.
        ext = self.pool.reserve(n_h - n_t, timeout=0.01)
        if ext is None:
            return shared, partial_page, partial_len, prefix_len
        if partial_len:
            # The promoted prefix covers (and extends past) the
            # divergent partial page — drop its ref, no CoW needed.
            self.pool.free([partial_page])
            partial_page, partial_len = None, 0
        self.engine.promote_pages(entry["pages"], shared + ext,
                                  n_skip=n_t)
        tier.note_promote()
        self._trace_emit(ticket.rid, "page_promote", pages=len(ext),
                         prefix_len=n_h * page)
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.counter(telemetry.SERVE_PAGE_PROMOTES_TOTAL).inc(
                len(ext))
        return shared + ext, None, 0, n_h * page

    def _maybe_demote(self, state):
        """Demote: at turn completion, snapshot the slot's full
        written pages to the host tier keyed by their token history,
        so the NEXT conversation turn (prompt = this turn's prompt +
        continuation) promotes them back instead of re-prefilling.
        Tick thread, BEFORE the pages return to the pool — the
        snapshot executable reads the live cache."""
        tier = self.host_tier
        request = state.request
        if tier is None or request.max_new_tokens <= 1:
            return
        from cloud_tpu.training.checkpoint import tree_digest
        emitted = [int(t)
                   for t in state.emitted[:request.max_new_tokens]]
        full = [int(t) for t in request.prompt] + emitted
        # The final sampled token was never written to the cache.
        written = len(full) - 1
        n_full = written // self.pool.page_size
        if n_full < 1 or n_full > len(state.pages):
            return
        key = full[:n_full * self.pool.page_size]
        if tier.contains(key):
            return
        host_tree = self.engine.snapshot_pages(state.pages[:n_full])
        if not tier.put(key, host_tree, n_full,
                        tree_digest(host_tree)):
            return  # oversized for the tier budget — refused, not LRUed
        self._trace_emit(state.rid, "page_demote", pages=n_full,
                         tokens=len(key))
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.counter(telemetry.SERVE_PAGE_DEMOTES_TOTAL).inc(n_full)

    def _distribute(self, fetched, elapsed):
        n_active = sum(s is not None for s in self._slots)
        if n_active:
            self._token_hist.observe(elapsed, count=n_active)
            # Geometry stamp: tick latency and occupancy roll up under
            # the rung this tick RAN at (kernel_costs() is likewise
            # keyed per geometry), never a mixed aggregate.
            g = self._geom()
            g["ticks"] += 1
            g["active_sum"] += n_active
            g["tick_hist"].observe(elapsed)
            reg = _registry()
            if reg is not None:
                from cloud_tpu.monitoring import telemetry
                reg.histogram(telemetry.SERVE_TOKEN_HISTOGRAM).observe(
                    elapsed, count=n_active)
                reg.histogram(
                    telemetry.SERVE_TICK_SECONDS
                    % self.engine.slots).observe(elapsed)
                # Kernel cost rows: one tick's paged-attention flops /
                # bytes over its measured wall time — pct_peak and
                # bytes_moved track the fused-kernel A/B alongside the
                # token-latency p99 this histogram already exports.
                for name, cost in self.engine.kernel_costs().items():
                    telemetry.get().record_kernel_cost(
                        name, cost["flops"], cost["bytes_moved"],
                        elapsed)
        if self.engine.spec_on:
            self._distribute_spec(fetched)
        else:
            self._distribute_plain(fetched)
        trace = self._trace
        if trace is not None:
            # Batched tick commits: one event per tick_every ticks per
            # surviving slot (finished slots emit `complete` instead),
            # carrying committed-token progress and batch occupancy —
            # the slot-occupancy timeline without per-token event cost.
            every = trace.tick_every
            for state in self._slots:
                if state is None or state.rid is None:
                    continue
                state.trace_ticks += 1
                if state.trace_ticks >= every:
                    state.trace_ticks = 0
                    trace.emit(state.rid, "tick_commit",
                               tokens_committed=len(state.emitted),
                               active_slots=n_active,
                               ticks=self._ticks,
                               slots=self.engine.slots)

    def _distribute_plain(self, fetched):
        tokens_row, finished_row = fetched[0], fetched[1]
        evict_mask = np.zeros((self.engine.slots,), bool)
        for slot, state in enumerate(self._slots):
            if state is None:
                continue
            state.emitted.append(int(tokens_row[slot]))
            if finished_row[slot]:
                self._finish_slot(slot, state, evict_mask)
        if evict_mask.any():
            self.engine.evict(evict_mask)
            self._observe_gauges()

    def _distribute_spec(self, fetched):
        from cloud_tpu.models.speculative import observe_accept_rate

        k = self.engine.spec_k
        count_row = fetched[k + 1]
        finished_row = fetched[k + 2]
        accept_row = fetched[k + 3]
        evict_mask = np.zeros((self.engine.slots,), bool)
        for slot, state in enumerate(self._slots):
            if state is None:
                continue
            c = int(count_row[slot])
            state.emitted.extend(
                int(fetched[j][slot]) for j in range(c))
            n_acc = int(accept_row[slot])
            if n_acc >= 0:
                self._accepted_draft_tokens += n_acc
                self._proposed_draft_tokens += k
                observe_accept_rate(n_acc, k)
            if finished_row[slot]:
                self._finish_slot(slot, state, evict_mask)
        if evict_mask.any():
            self.engine.evict(evict_mask)
            self._observe_gauges()

    def _finish_slot(self, slot, state, evict_mask):
        evict_mask[slot] = True
        self._slots[slot] = None
        self._free_slots.append(slot)
        self._maybe_demote(state)
        self.pool.free(state.pages)
        self._complete(state.request, state.future, state.t_submit,
                       state.ttft_s, state.emitted,
                       prefix_len=state.result_prefix_len,
                       rid=state.rid)

    def _complete(self, request, future, t_submit, ttft, emitted,
                  prefix_len, rid=None):
        # A speculative tick can overshoot max_new_tokens by up to
        # spec_k accepted tokens — the greedy chain is identical, so
        # truncation is exact.
        emitted = emitted[:request.max_new_tokens]
        # Early-eos eviction: generate() keeps emitting eos after done,
        # so the bit-identical fill is pure host work.
        if len(emitted) < request.max_new_tokens:
            emitted = emitted + [request.eos_token] * (
                request.max_new_tokens - len(emitted))
        tokens = np.concatenate([
            np.asarray(request.prompt, np.int32),
            np.asarray(emitted, np.int32)])
        latency = time.monotonic() - t_submit
        self._completed += 1
        self._tokens_out += request.max_new_tokens
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.counter(telemetry.SERVE_REQUESTS_TOTAL).inc()
            reg.counter(telemetry.SERVE_TOKENS_TOTAL).inc(
                request.max_new_tokens)
            wall = max(time.monotonic() - self._t_start, 1e-9)
            reg.gauge(telemetry.SERVE_REQUESTS_PER_SEC).set(
                self._completed / wall)
        self._trace_emit(rid, "complete", ttft_s=ttft,
                         latency_s=latency,
                         tokens=int(request.max_new_tokens),
                         prefix_len=int(prefix_len))
        future.set_result(ServeResult(tokens=tokens, ttft_s=ttft,
                                      latency_s=latency,
                                      prefix_len=prefix_len))

    # -- shared helpers -----------------------------------------------

    def _trace_emit(self, rid, event, **fields):
        trace = self._trace
        if trace is not None and rid is not None:
            trace.emit(rid, event, **fields)

    def _trace_fail(self, rid, error):
        self._trace_emit(rid, "fail", error="{}: {}".format(
            type(error).__name__, str(error)[:200]))

    def _observe_reserve_wait(self, wait):
        self._reserve_wait_hist.observe(wait)
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.histogram(
                telemetry.SERVE_RESERVE_WAIT_HISTOGRAM).observe(wait)

    def _observe_queue(self):
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.gauge(telemetry.SERVE_QUEUE_DEPTH).set(
                self._admit_q.qsize())

    def _observe_gauges(self):
        reg = _registry()
        if reg is None:
            return
        from cloud_tpu.monitoring import telemetry
        reg.gauge(telemetry.SERVE_ACTIVE_SLOTS).set(
            sum(s is not None for s in self._slots))
        reg.gauge(telemetry.SERVE_SLOT_COUNT).set(self.engine.slots)
        reg.gauge(telemetry.SERVE_QUEUE_DEPTH).set(
            self._admit_q.qsize())
        pstats = self.pool.pool_stats()
        reg.gauge(telemetry.SERVE_PAGES_FREE).set(pstats["pages_free"])
        reg.gauge(telemetry.SERVE_PAGES_SHARED).set(
            pstats["pages_shared"])
        reg.gauge(telemetry.SERVE_COW_COPIES).set(pstats["cow_copies"])
        reg.gauge(telemetry.SERVE_RESERVE_WAITERS).set(
            pstats["reserve_waiters"])
        reg.gauge(telemetry.SERVE_PAGES_PREFILLING).set(
            pstats["pages_prefilling"])
        reg.gauge(telemetry.SERVE_KV_BYTES % "hbm").set(
            pstats["kv_bytes_held"])
        reg.gauge(telemetry.SERVE_KV_CAPACITY_SESSIONS).set(
            self.pool.capacity // self.engine.pages_per_slot)
        if self.host_tier is not None:
            hstats = self.host_tier.stats()
            reg.gauge(telemetry.SERVE_HOST_TIER_PAGES).set(
                hstats["pages"])
            reg.gauge(telemetry.SERVE_KV_BYTES % "host").set(
                hstats["pages"] * self.pool.page_bytes)
        if self.trie is not None:
            tstats = self.trie.stats()
            reg.gauge(telemetry.SERVE_PREFIX_PAGES_HELD).set(
                tstats["pages_held"])
            reg.gauge(telemetry.SERVE_PREFIX_EVICTIONS).set(
                tstats["evictions"])

    def _fail_pending(self, error):
        with self._ready_lock:
            ready, self._ready = list(self._ready), collections.deque()
            chunks, self._chunks = (list(self._chunks),
                                    collections.deque())
        for item in ready:
            if isinstance(item, _ChunkItem):
                chunks.append(item)
                continue
            if isinstance(item, _ReadyItem) and item.pages:
                self.pool.free(item.pages)
            if not item.future.done():
                self._trace_fail(item.rid, error)
                item.future.set_exception(error)
        for item in chunks:
            self._fail_chunk_item(item, error)
        self._pending_inserts = 0
        with self._ready_lock:
            self._chunk_accounted = 0
        for slot, state in enumerate(self._slots):
            if state is not None:
                if state.pages:
                    self.pool.free(state.pages)
                if not state.future.done():
                    self._trace_fail(state.rid, error)
                    state.future.set_exception(error)
            self._slots[slot] = None
        while True:
            try:
                _, future, _, rid, _ = self._admit_q.get_nowait()
            except queue.Empty:
                break
            if not future.done():
                self._trace_fail(rid, error)
                future.set_exception(error)

    # -- invariants ---------------------------------------------------

    def assert_drained(self, clear_prefix=False):
        """Refcount leak detector. With no in-flight work, every held
        pool page must be exactly one trie reference (refcount 1, page
        indexed); with `clear_prefix` the trie is dropped first and the
        pool must be FULLY free. Raises RuntimeError on any leak."""
        busy = (any(s is not None for s in self._slots)
                or self._pending_inserts > 0 or self._admit_q.qsize())
        with self._ready_lock:
            busy = busy or bool(self._ready) or bool(self._chunks)
        if busy:
            raise RuntimeError(
                "assert_drained called with requests in flight.")
        if clear_prefix and self.trie is not None:
            self.trie.clear()
        held = self.pool.leak_report()
        trie_pages = (set(self.trie.held_pages())
                      if self.trie is not None else set())
        leaked = {p: r for p, r in held.items()
                  if p not in trie_pages or r != 1}
        if leaked:
            raise RuntimeError(
                "page refcount leak (page -> holders, beyond the "
                "prefix index): {}".format(leaked))
        if len(held) != len(trie_pages):
            raise RuntimeError(
                "prefix index holds {} pages but the pool records {} "
                "held.".format(len(trie_pages), len(held)))

    # -- warm-up + stats ----------------------------------------------

    def warmup(self, buckets, sampling_configs=((),), max_new=3):
        """Compiles the whole serving surface for `buckets` x sampling
        configs: per-bucket prefill (full and short lengths), insert,
        tick, evict, and the cache-reuse re-zero. Two sequential waves
        so the second wave's prefills acquire parked caches (compiling
        the in-place zero executable). With the prefix cache on, every
        pow2 width up to the largest bucket is warmed too (a hit's
        SUFFIX can land in any of them) and a shared-prefix trio
        compiles the gather + copy-on-write path; the trie is cleared
        afterwards so warm-up leaves no cached state. Call
        `engine.mark_warm()` is implicit — after warmup the retrace
        sentinel is armed."""
        from cloud_tpu.models.decoding import bucket_length

        # Warm-up requests are synthetic: stamp no rids and emit no
        # trace events, so every traced lifecycle in the JSONL is real
        # traffic and the zero-orphans CI assertion stays meaningful.
        self._trace_suppress = True
        vocab = self.engine.model.vocab_size
        configs = []
        for cfg in sampling_configs:
            merged = dict(temperature=0.0, top_k=None, top_p=None,
                          eos_token=None)
            merged.update(dict(cfg))
            configs.append(merged)
        widths = set(buckets)
        if self.trie is not None and buckets:
            w = 1
            while w <= max(buckets):
                widths.add(w)
                w *= 2
        # Distinct first tokens keep warm-up prompts from prefix-
        # matching EACH OTHER — a warm-up hit would compile its suffix
        # bucket instead of the width it was meant to compile.
        combo = 0
        # Widest buckets can't host a full-length prompt AND max_new
        # decode positions — cap warm-up lengths so the request
        # validates; bucket_length() still maps the capped length to
        # the intended width.
        cap = self.engine.max_seq_len - max_new - self._spec_slack()
        chunk_lengths = []
        if self._prefill_chunk is not None:
            # Drive the chunk + tail-bucket surface: length C + t has
            # exactly one full chunk and a t-token tail, so the set
            # {C + t : t pow2 <= C} compiles the fixed-chunk executable
            # and EVERY tail bucket per sampling config. Steady state
            # then stays at zero new traces regardless of prompt
            # length — any n decomposes into full chunks + one of
            # these tails.
            t = 1
            while t <= self._prefill_chunk:
                if self._prefill_chunk + t <= cap:
                    chunk_lengths.append(self._prefill_chunk + t)
                t *= 2
        for _ in range(2):
            futures = []
            for bucket in sorted(widths):
                for length in sorted({min(bucket, cap),
                                      min(max(bucket - 1, 1), cap)}):
                    if length < 1 or bucket_length(
                            length, self.engine.max_seq_len) != bucket:
                        continue
                    for cfg in configs:
                        first = 2 + combo % max(vocab - 2, 1)
                        combo += 1
                        futures.append(self.submit(ServeRequest(
                            prompt=[first] + [1] * (length - 1),
                            max_new_tokens=max_new, **cfg)))
            for length in chunk_lengths:
                for cfg in configs:
                    first = 2 + combo % max(vocab - 2, 1)
                    combo += 1
                    futures.append(self.submit(ServeRequest(
                        prompt=[first] + [1] * (length - 1),
                        max_new_tokens=max_new, **cfg)))
            for future in futures:
                future.result(timeout=600)
        if self.trie is not None:
            self._warm_prefix_path(configs[0])
            if self.host_tier is not None:
                self._warm_host_tier(configs[0])
                self.host_tier.clear()
                self.host_tier.reset_stats()
            self.trie.clear()
            self.trie.reset_stats()
        self._warm_ladder(configs[0], max_new)
        self.engine.mark_warm()
        self._trace_suppress = False
        # Warm-up TTFTs are compile times; restart the host-side stats
        # so `stats()` describes warm traffic only.
        from cloud_tpu.monitoring.telemetry import Histogram
        self._ttft_hist = Histogram("ttft")
        self._ttft_hit_hist = Histogram("ttft_hit")
        self._ttft_miss_hist = Histogram("ttft_miss")
        self._token_hist = Histogram("token_latency")
        self._queue_wait_hist = Histogram("queue_wait")
        self._reserve_wait_hist = Histogram("reserve_wait")
        self._prefill_hist = Histogram("prefill")
        self._prefill_chunk_hist = Histogram("prefill_chunk")
        self._decode_gap_hist = Histogram("decode_gap")
        self._chunks_dispatched = 0
        self._t_last_commit = None
        self._completed = 0
        self._tokens_out = 0
        self._ticks = 0
        self._hits = 0
        self._misses = 0
        self._prefix_tokens_served = 0
        self._accepted_draft_tokens = 0
        self._proposed_draft_tokens = 0
        self._resize_counts = {"grow": 0, "shrink": 0}
        self._resize_events = []
        self._quiet_ticks = 0
        self._geom_stats = {}
        self._admission_model_hits = 0
        self._t_start = time.monotonic()

    def _warm_ladder(self, cfg, max_new):
        """graftflex ladder walk: visits every rung (start -> min ->
        max -> start, one rung per step) so EACH adjacent resize pair
        compiles in BOTH directions, and runs a small decode wave the
        first time a rung is visited — tick/insert/evict trace per
        slot count, so steady-state traffic on any rung, with policy
        resizes in between, stays at zero new traces. The walk ends
        back on the starting rung. Prefill executables are dense
        [1, L] and geometry-free; the main waves already warmed them.
        """
        ladder = self.engine.ladder
        if len(ladder) <= 1:
            return
        start = self.engine.slots
        idx = ladder.index(start)
        targets = (list(ladder[:idx][::-1])       # start -> min
                   + list(ladder)                 # min -> max
                   + list(ladder[idx:-1][::-1]))  # max -> start
        vocab = self.engine.model.vocab_size
        visited = {start}
        combo = 0
        for rung in targets:
            if rung == self.engine.slots:
                continue
            self.request_resize(rung, reason="warmup", timeout=600)
            if rung in visited:
                continue
            visited.add(rung)
            futures = []
            for _ in range(2):
                first = 2 + combo % max(vocab - 2, 1)
                combo += 1
                futures.append(self.submit(ServeRequest(
                    prompt=[first], max_new_tokens=max_new, **cfg)))
            for future in futures:
                future.result(timeout=600)

    def _warm_prefix_path(self, cfg):
        """Shared-prefix trio: a miss that registers a page, a mid-page
        divergence (gather + CoW reconstruction), and a clean full-page
        hit — compiles the gather executables (target and draft trees)
        and exercises the hit insert before the sentinel arms."""
        page = self.pool.page_size
        vocab = self.engine.model.vocab_size
        base_len = page + page // 2
        if (page < 4 or vocab < 4 or base_len + 2 + self._spec_slack()
                > self.engine.max_seq_len):
            return
        base = [1] * base_len
        prompts = [
            base,                                       # miss, registers
            base[:(3 * page) // 4] + [2] * (base_len - (3 * page) // 4),
            base[:page] + [3] * (base_len - page),      # full-page hit
        ]
        for prompt in prompts:
            self.submit(ServeRequest(prompt=prompt, max_new_tokens=2,
                                     **cfg)).result(timeout=600)

    def _warm_host_tier(self, cfg):
        """graftpack pair: a turn that completes and demotes two full
        pages (compiling the snapshot executable), then its next turn,
        whose admission finds the host entry past the one-page trie
        prefix and promotes (compiling the promote scatter and the
        wider-prefix gather) — so steady-state offload traffic stays
        at zero new traces. Both executables are fixed-shape, so one
        compile each covers every page count."""
        page = self.pool.page_size
        vocab = self.engine.model.vocab_size
        if (page < 2 or vocab < 5
                or page + 2 > self.engine.max_new_cap
                or 2 * page + 5 + self._spec_slack()
                > self.engine.max_seq_len):
            return
        first = self.submit(ServeRequest(
            prompt=[4] * page, max_new_tokens=page + 2,
            **cfg)).result(timeout=600)
        turn2 = [int(t) for t in first.tokens] + [2]
        self.submit(ServeRequest(prompt=turn2, max_new_tokens=2,
                                 **cfg)).result(timeout=600)

    def stats(self):
        """Host-side rollup for bench/smoke (works with telemetry
        off)."""
        wall = max(time.monotonic() - (self._t_start or
                                       time.monotonic()), 1e-9)
        lookups = self._hits + self._misses
        proposed = self._proposed_draft_tokens
        out = {
            "requests_completed": self._completed,
            "tokens_emitted": self._tokens_out,
            "ticks": self._ticks,
            "elapsed_seconds": wall,
            "requests_per_sec": self._completed / wall,
            "tokens_per_sec": self._tokens_out / wall,
            "ttft": self._ttft_hist.snapshot(),
            "ttft_hit": self._ttft_hit_hist.snapshot(),
            "ttft_miss": self._ttft_miss_hist.snapshot(),
            "token_latency": self._token_hist.snapshot(),
            "queue_wait": self._queue_wait_hist.snapshot(),
            "reserve_wait": self._reserve_wait_hist.snapshot(),
            "prefill": self._prefill_hist.snapshot(),
            "prefill_chunk": self._prefill_chunk_hist.snapshot(),
            "decode_gap": self._decode_gap_hist.snapshot(),
            "prefill_chunks_dispatched": self._chunks_dispatched,
            "prefill_chunk_size": self._prefill_chunk or 0,
            "queue_depth": self._admit_q.qsize(),
            "faults": dict(self._fault_counts),
            "requeues": self._requeues,
            "shed": dict(self._shed_counts),
            "predicted_ttft": self._last_predicted_ttft,
            "slo_ttft": self._slo_ttft,
            "shed_policy": self._shed_policy,
            "prefix_hits": self._hits,
            "prefix_misses": self._misses,
            "prefix_hit_rate": self._hits / lookups if lookups else 0.0,
            "prefix_tokens_served": self._prefix_tokens_served,
            "pool": self.pool.pool_stats(),
            "spec_accept_rate": (self._accepted_draft_tokens / proposed
                                 if proposed else 0.0),
            "spec_accepted_tokens": self._accepted_draft_tokens,
            "spec_proposed_tokens": proposed,
        }
        # graftflex geometry rollup: the current rung, the ladder, the
        # resize census, and every per-tick stat split by the geometry
        # it ran under — the aggregate histograms above stay for
        # back-compat, but cross-width comparisons must read this.
        geoms = {}
        for s, g in sorted(self._geom_stats.items()):
            geoms[str(s)] = {
                "ticks": g["ticks"],
                "occupancy_mean": (g["active_sum"] / g["ticks"]
                                   if g["ticks"] else 0.0),
                "tick_latency": g["tick_hist"].snapshot(),
                "decode_gap": g["decode_gap_hist"].snapshot(),
                "kernel_costs": self.engine.kernel_costs(s),
            }
        out["geometry"] = {
            "slots": self.engine.slots,
            "ladder": list(self.engine.ladder),
            "resizes": dict(self._resize_counts),
            "resize_events": list(self._resize_events),
            "per_geometry": geoms,
        }
        out["admission_predictor"] = {
            "loaded": self._admission_model is not None,
            "path": self._admission_model_path,
            "error": self._admission_model_error,
            "predictions": self._admission_model_hits,
        }
        # graftpack KV hierarchy rollup: dtype-aware byte accounting
        # plus the demote/promote census, mirrored from the host tier.
        hstats = (self.host_tier.stats() if self.host_tier is not None
                  else None)
        out["kv"] = {
            "page_dtype": self.kv_dtype,
            "page_bytes": self.pool.page_bytes,
            "capacity_sessions": (self.pool.capacity
                                  // self.engine.pages_per_slot),
            "host_tier_pages": hstats["pages"] if hstats else 0,
            "page_demotes": hstats["demotes"] if hstats else 0,
            "page_promotes": hstats["promotes"] if hstats else 0,
            "digest_failures": (hstats["digest_failures"]
                                if hstats else 0),
        }
        if hstats is not None:
            out["host_tier"] = hstats
        if self.trie is not None:
            out["prefix_cache"] = self.trie.stats()
        return out


__all__ = ["ServeRequest", "ServeResult", "Scheduler"]
