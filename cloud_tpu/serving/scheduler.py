"""graftserve request scheduler: admission, batching, backpressure.

Two threads around a `DecodeEngine`:

- the ADMISSION thread pops submitted requests from a bounded queue in
  FCFS windows, orders each window longest-prefix-first (big pow2
  prefill buckets first — they hold their slot longest, so starting
  them earliest minimizes tail latency), reserves KV pages (BLOCKING
  when the pool is exhausted — backpressure, never OOM), and runs the
  dense prefill off the tick's critical path;
- the TICK thread owns the engine's device state: it inserts ready
  prefills into free slots, advances all active slots one token per
  tick, fetches the tick output (the serving loop's single counted d2h
  round trip), completes/evicts finished slots, and returns their
  pages.

Liveness rides graftwatch: the tick thread beats the installed watchdog
every iteration and polls `watch.check()`, so a stuck tick surfaces as
the watchdog's typed fault (graftwatch blackbox + `BackendUnavailable`)
instead of a silent hang. Throughput/latency ride graftscope: requests
and tokens totals, queue-depth and active-slots gauges, TTFT and
per-token latency histograms (p50/p95/p99 via the registry snapshot).

Phase labels: the tick thread runs under `runtime.set_phase
("serve_tick")`, the admission thread under "serve_prefill" — distinct
from the training "step" phase, so graftsan GS001 (d2h-in-step-loop)
correctly treats the per-tick fetch as a sanctioned, attributed read.
"""

import collections
import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional, Sequence

import jax
import numpy as np

from cloud_tpu.parallel import runtime
from cloud_tpu.serving.engine import DecodeEngine
from cloud_tpu.serving.kvpool import PagePool


@dataclasses.dataclass
class ServeRequest:
    """One decode request. Semantics (and output) match
    `generate(model, params, prompt[None], max_new_tokens,
    rng=PRNGKey(rng_seed), ...)` exactly — the determinism contract."""
    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_token: Optional[int] = None
    rng_seed: int = 0


@dataclasses.dataclass
class ServeResult:
    """A completed request: `tokens` is prompt + continuation, the
    `generate()` row contract."""
    tokens: np.ndarray
    ttft_s: float
    latency_s: float


class _Slot:
    __slots__ = ("request", "pages", "emitted", "future", "t_submit",
                 "ttft_s")

    def __init__(self, request, pages, future, t_submit, ttft_s):
        self.request = request
        self.pages = pages
        self.emitted = []
        self.future = future
        self.t_submit = t_submit
        self.ttft_s = ttft_s


def _registry():
    """graftscope registry when telemetry is enabled, else None — the
    decode hooks' zero-cost-when-off discipline."""
    import sys
    telemetry = sys.modules.get("cloud_tpu.monitoring.telemetry")
    if telemetry is None:
        return None
    tele = telemetry.get()
    if tele is None or not tele.active:
        return None
    return tele.registry


class Scheduler:
    """Continuous-batching front door. `submit()` from any thread;
    results come back as futures resolving to `ServeResult`."""

    def __init__(self, model, params, slots=4, page_size=16,
                 num_pages=None, max_new_cap=None, max_queue=64,
                 admission_window=8, strict_no_retrace=False):
        if num_pages is None:
            # Default: every slot can hold a full-length sequence, plus
            # scratch — paging then bounds fragmentation, not memory.
            num_pages = slots * (model.max_seq_len // page_size) + 1
        self.engine = DecodeEngine(model, params, slots, page_size,
                                   num_pages, max_new_cap=max_new_cap)
        self.pool = PagePool(num_pages, page_size,
                             self.engine.pages_per_slot)
        self.strict_no_retrace = bool(strict_no_retrace)
        self._admission_window = int(admission_window)
        self._admit_q = queue.Queue(maxsize=max_queue)
        self._ready = collections.deque()
        self._ready_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._failure = None
        self._slots = [None] * self.engine.slots
        self._free_slots = list(range(self.engine.slots))
        self._started = False
        self._t_start = None
        self._completed = 0
        self._tokens_out = 0
        self._ticks = 0
        # Requests admitted but not yet slot-resident. While > 0 and
        # slots are free, the tick loop briefly yields so inserts land
        # before the next tick — a tick advancing 2 of 8 slots costs
        # the same device work as a full one (the batch-synchronous
        # waste this engine exists to avoid).
        self._pending_inserts = 0
        from cloud_tpu.monitoring.telemetry import Histogram
        self._ttft_hist = Histogram("ttft")
        self._token_hist = Histogram("token_latency")

    # -- lifecycle ----------------------------------------------------

    def start(self):
        if self._started:
            return self
        self._started = True
        self._t_start = time.monotonic()
        self._prefill_thread = threading.Thread(
            target=self._prefill_loop, name="graftserve-prefill",
            daemon=True)
        self._tick_thread = threading.Thread(
            target=self._tick_loop, name="graftserve-tick", daemon=True)
        self._prefill_thread.start()
        self._tick_thread.start()
        return self

    def close(self):
        """Stops both threads; pending/queued requests fail with a
        RuntimeError (or the loop's typed fault, if one fired)."""
        if not self._started:
            return
        self._stop.set()
        self.pool.close()
        self._wake.set()
        self._prefill_thread.join(timeout=30)
        self._tick_thread.join(timeout=30)
        error = self._failure or RuntimeError("scheduler closed")
        self._fail_pending(error)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # -- submission ---------------------------------------------------

    def submit(self, request, timeout=None):
        """Admits one request; returns a Future[ServeResult]. Blocks
        (then raises queue.Full) when the bounded admission queue is
        full — backpressure, by design, reaches the caller."""
        if self._failure is not None:
            raise self._failure
        self._validate(request)
        future = Future()
        t_submit = time.monotonic()
        if request.max_new_tokens == 0:
            future.set_result(ServeResult(
                tokens=np.asarray(request.prompt, np.int32),
                ttft_s=0.0, latency_s=0.0))
            return future
        if request.max_new_tokens > 1:
            self._pending_inserts += 1
        self._admit_q.put((request, future, t_submit), timeout=timeout)
        self._observe_queue()
        return future

    def _validate(self, request):
        model = self.engine.model
        prompt_len = len(request.prompt)
        if prompt_len < 1:
            raise ValueError("prompt must be non-empty.")
        if request.max_new_tokens < 0:
            raise ValueError("max_new_tokens must be >= 0.")
        if prompt_len + request.max_new_tokens > model.max_seq_len:
            raise ValueError(
                "prompt ({}) + max_new_tokens ({}) exceeds max_seq_len "
                "{}.".format(prompt_len, request.max_new_tokens,
                             model.max_seq_len))
        if request.max_new_tokens > self.engine.max_new_cap:
            raise ValueError(
                "max_new_tokens ({}) exceeds the engine's max_new_cap "
                "({}).".format(request.max_new_tokens,
                               self.engine.max_new_cap))
        if request.top_k is not None and not (
                1 <= request.top_k <= model.vocab_size):
            raise ValueError("top_k must be in [1, vocab_size={}]; got "
                             "{}.".format(model.vocab_size,
                                          request.top_k))
        if request.top_p is not None and not (
                0.0 < request.top_p <= 1.0):
            raise ValueError("top_p must be in (0, 1]; got {}.".format(
                request.top_p))
        if request.max_new_tokens > 1:
            # Raises when no reservation could EVER satisfy it.
            need = self.pool.pages_needed(self._bucket(request),
                                          request.max_new_tokens)
            if need > self.pool.capacity:
                raise ValueError(
                    "request needs {} pages; the pool has {} "
                    "allocatable.".format(need, self.pool.capacity))

    def _bucket(self, request):
        from cloud_tpu.models.decoding import bucket_length
        return bucket_length(
            len(request.prompt),
            self.engine.max_seq_len - request.max_new_tokens)

    @staticmethod
    def _sampling(request):
        return {
            "temperature": float(request.temperature),
            "top_k": None if request.top_k is None
            else int(request.top_k),
            "top_p": None if request.top_p is None
            else float(request.top_p),
            "eos_token": None if request.eos_token is None
            else int(request.eos_token),
        }

    # -- admission/prefill thread -------------------------------------

    def _prefill_loop(self):
        runtime.set_phase("serve_prefill")
        while not self._stop.is_set():
            window = self._next_window()
            if not window:
                continue
            # Longest-prefix-first within the FCFS window (stable sort:
            # equal buckets stay FCFS).
            window.sort(key=lambda item: -self._bucket(item[0]))
            for request, future, t_submit in window:
                if self._stop.is_set():
                    return
                try:
                    self._admit_one(request, future, t_submit)
                except BaseException as exc:  # noqa: BLE001
                    if request.max_new_tokens > 1:
                        self._pending_inserts -= 1
                    future.set_exception(exc)

    def _next_window(self):
        window = []
        try:
            window.append(self._admit_q.get(timeout=0.05))
        except queue.Empty:
            return window
        while len(window) < self._admission_window:
            try:
                window.append(self._admit_q.get_nowait())
            except queue.Empty:
                break
        self._observe_queue()
        return window

    def _admit_one(self, request, future, t_submit):
        sampling = self._sampling(request)
        pages = []
        if request.max_new_tokens > 1:
            need = self.pool.pages_needed(self._bucket(request),
                                          request.max_new_tokens)
            while not self._stop.is_set():
                pages = self.pool.reserve(need, timeout=0.2)
                if pages is not None:
                    break
            if pages is None:  # shutdown while blocked on the pool
                self._pending_inserts -= 1
                future.set_exception(RuntimeError("scheduler closed"))
                return
        try:
            result = self.engine.prefill(
                np.asarray(request.prompt, np.int32),
                request.max_new_tokens,
                jax.random.PRNGKey(request.rng_seed), sampling)
        except BaseException:
            if pages:
                self.pool.free(pages)
            raise
        ttft = time.monotonic() - t_submit
        self._ttft_hist.observe(ttft)
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.histogram(telemetry.SERVE_TTFT_HISTOGRAM).observe(ttft)
        if request.max_new_tokens == 1:
            # Completes at prefill: no slot, no pages, no tick.
            self.engine.release_prefill(result)
            self._complete(request, future, t_submit, ttft,
                           [result.first_token])
            return
        with self._ready_lock:
            self._ready.append(_ReadyItem(request, result, pages,
                                          future, t_submit, ttft))
        self._wake.set()

    # -- tick thread --------------------------------------------------

    def _tick_loop(self):
        runtime.set_phase("serve_tick")
        from cloud_tpu.monitoring import watch
        # Adopt an installed graftwatch: the tick thread becomes the
        # beat source AND the async-raise target, so a stuck tick is
        # the thread the stall fault interrupts (typed
        # BackendUnavailable + blackbox), not a silent hang.
        watch.rewatch()
        skips = 0
        try:
            while not self._stop.is_set():
                if watch.enabled():
                    watch.heartbeat()
                    watch.check()
                self._insert_ready()
                if not any(s is not None for s in self._slots):
                    if not self._wake.wait(timeout=0.05):
                        continue
                    self._wake.clear()
                    continue
                if (self._free_slots and self._pending_inserts > 0
                        and skips < 40):
                    # Admissions are in flight and slots are open:
                    # yield briefly so the insert lands before the
                    # next tick. The skip cap bounds the stall when an
                    # admission is itself blocked on pages only ticks
                    # can free.
                    skips += 1
                    self._wake.wait(timeout=0.005)
                    self._wake.clear()
                    continue
                skips = 0
                t0 = time.monotonic()
                out = self.engine.tick()
                fetched = runtime.device_fetch(out)
                elapsed = time.monotonic() - t0
                self._ticks += 1
                self._distribute(fetched, elapsed)
                if self.strict_no_retrace:
                    self.engine.check_no_retrace()
        except BaseException as exc:  # noqa: BLE001
            self._failure = exc
            self._stop.set()
            self.pool.close()
            self._fail_pending(exc)

    def _insert_ready(self):
        while self._free_slots:
            with self._ready_lock:
                if not self._ready:
                    return
                item = self._ready.popleft()
            slot = self._free_slots.pop()
            state = _Slot(item.request, item.pages, item.future,
                          item.t_submit, item.ttft_s)
            state.emitted.append(item.result.first_token)
            self._slots[slot] = state
            self.engine.insert(slot, item.result,
                               self.pool.page_vec(item.pages),
                               self._sampling(item.request))
            self._pending_inserts -= 1
            self._observe_gauges()

    def _distribute(self, fetched, elapsed):
        tokens_row, finished_row = fetched[0], fetched[1]
        n_active = sum(s is not None for s in self._slots)
        if n_active:
            self._token_hist.observe(elapsed, count=n_active)
            reg = _registry()
            if reg is not None:
                from cloud_tpu.monitoring import telemetry
                reg.histogram(telemetry.SERVE_TOKEN_HISTOGRAM).observe(
                    elapsed, count=n_active)
        evict_mask = np.zeros((self.engine.slots,), bool)
        for slot, state in enumerate(self._slots):
            if state is None:
                continue
            state.emitted.append(int(tokens_row[slot]))
            if finished_row[slot]:
                evict_mask[slot] = True
                self._slots[slot] = None
                self._free_slots.append(slot)
                self.pool.free(state.pages)
                self._complete(state.request, state.future,
                               state.t_submit, state.ttft_s,
                               state.emitted)
        if evict_mask.any():
            self.engine.evict(evict_mask)
            self._observe_gauges()

    def _complete(self, request, future, t_submit, ttft, emitted):
        # Early-eos eviction: generate() keeps emitting eos after done,
        # so the bit-identical fill is pure host work.
        if len(emitted) < request.max_new_tokens:
            emitted = emitted + [request.eos_token] * (
                request.max_new_tokens - len(emitted))
        tokens = np.concatenate([
            np.asarray(request.prompt, np.int32),
            np.asarray(emitted, np.int32)])
        latency = time.monotonic() - t_submit
        self._completed += 1
        self._tokens_out += request.max_new_tokens
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.counter(telemetry.SERVE_REQUESTS_TOTAL).inc()
            reg.counter(telemetry.SERVE_TOKENS_TOTAL).inc(
                request.max_new_tokens)
            wall = max(time.monotonic() - self._t_start, 1e-9)
            reg.gauge(telemetry.SERVE_REQUESTS_PER_SEC).set(
                self._completed / wall)
        future.set_result(ServeResult(tokens=tokens, ttft_s=ttft,
                                      latency_s=latency))

    # -- shared helpers -----------------------------------------------

    def _observe_queue(self):
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.gauge(telemetry.SERVE_QUEUE_DEPTH).set(
                self._admit_q.qsize())

    def _observe_gauges(self):
        reg = _registry()
        if reg is not None:
            from cloud_tpu.monitoring import telemetry
            reg.gauge(telemetry.SERVE_ACTIVE_SLOTS).set(
                sum(s is not None for s in self._slots))
            reg.gauge(telemetry.SERVE_QUEUE_DEPTH).set(
                self._admit_q.qsize())

    def _fail_pending(self, error):
        self._pending_inserts = 0
        with self._ready_lock:
            ready, self._ready = list(self._ready), collections.deque()
        for item in ready:
            if not item.future.done():
                item.future.set_exception(error)
        for slot, state in enumerate(self._slots):
            if state is not None and not state.future.done():
                state.future.set_exception(error)
            self._slots[slot] = None
        while True:
            try:
                _, future, _ = self._admit_q.get_nowait()
            except queue.Empty:
                break
            if not future.done():
                future.set_exception(error)

    # -- warm-up + stats ----------------------------------------------

    def warmup(self, buckets, sampling_configs=((),), max_new=3):
        """Compiles the whole serving surface for `buckets` x sampling
        configs: per-bucket prefill (masked and exact-length variants),
        insert, tick, evict, and the cache-reuse re-zero. Two
        sequential waves so the second wave's prefills acquire parked
        caches (compiling the in-place zero executable). Call
        `engine.mark_warm()` is implicit — after warmup the retrace
        sentinel is armed."""
        configs = []
        for cfg in sampling_configs:
            merged = dict(temperature=0.0, top_k=None, top_p=None,
                          eos_token=None)
            merged.update(dict(cfg))
            configs.append(merged)
        for _ in range(2):
            futures = []
            for bucket in buckets:
                for length in {bucket, max(bucket - 1, 1)}:
                    if self._bucket(ServeRequest(
                            prompt=[1] * length,
                            max_new_tokens=max_new)) != bucket:
                        continue
                    for cfg in configs:
                        futures.append(self.submit(ServeRequest(
                            prompt=[1] * length,
                            max_new_tokens=max_new, **cfg)))
            for future in futures:
                future.result(timeout=600)
        self.engine.mark_warm()
        # Warm-up TTFTs are compile times; restart the host-side stats
        # so `stats()` describes warm traffic only.
        from cloud_tpu.monitoring.telemetry import Histogram
        self._ttft_hist = Histogram("ttft")
        self._token_hist = Histogram("token_latency")
        self._completed = 0
        self._tokens_out = 0
        self._ticks = 0
        self._t_start = time.monotonic()

    def stats(self):
        """Host-side rollup for bench/smoke (works with telemetry
        off)."""
        wall = max(time.monotonic() - (self._t_start or
                                       time.monotonic()), 1e-9)
        return {
            "requests_completed": self._completed,
            "tokens_emitted": self._tokens_out,
            "ticks": self._ticks,
            "elapsed_seconds": wall,
            "requests_per_sec": self._completed / wall,
            "tokens_per_sec": self._tokens_out / wall,
            "ttft": self._ttft_hist.snapshot(),
            "token_latency": self._token_hist.snapshot(),
            "queue_depth": self._admit_q.qsize(),
        }


class _ReadyItem:
    __slots__ = ("request", "result", "pages", "future", "t_submit",
                 "ttft_s")

    def __init__(self, request, result, pages, future, t_submit,
                 ttft_s):
        self.request = request
        self.result = result
        self.pages = pages
        self.future = future
        self.t_submit = t_submit
        self.ttft_s = ttft_s


__all__ = ["ServeRequest", "ServeResult", "Scheduler"]
