"""Paged KV-cache pool: host-side physical page accounting.

The physical pages themselves live in HBM as flax cache variables of the
paged decoder (`key_pages`/`value_pages` `[num_pages, page_size, H, D]`
per attention layer — models/transformer.py `_paged_decode_attention`).
This module owns the other half of the design: WHICH physical pages each
request holds. Reservation happens at admission (before any HBM is
touched for the request), so exhaustion surfaces as scheduler
backpressure — a blocked reserve — never as an OOM or a reshape/retrace
of the pool executable. The device side only ever sees page-id ARRAYS
(page-table rows), so allocation and free are in-graph index updates on
executables of fixed shape.

Page 0 is the scratch page: it is never handed out, and every freed or
never-filled page-table entry points at it. Inactive slots write their
(masked, never-attended) tick garbage there, which is what makes
cross-request leakage structurally impossible — a slot's table can only
reference pages reserved for it, or scratch.

Pages are REFERENCE COUNTED so multiple holders can map the same
physical page (the radix prefix cache shares populated prompt pages
across requests — serving/prefixcache.py). `reserve` hands out fresh
pages at refcount 1; `share` adds a holder to an already-allocated page;
`free` drops one holder and only recycles the page when the last holder
lets go. A shared page is immutable by convention: the holder that needs
to write past it makes a copy-on-write page first (the engine's insert
scatter routes shared entries to scratch and reconstructs divergent
content into fresh pages), and `note_cow` keeps the count for
`pool_stats`.
"""

import threading

import numpy as np


class PagePool:
    """Refcounting free-list allocator over `num_pages` physical pages.

    Thread-safe; `reserve` blocks (condition wait) until enough pages
    are free, which is the backpressure primitive the scheduler builds
    on. All bookkeeping is host-side python — the device never sees
    this object, only the page-id vectors it emits.
    """

    def __init__(self, num_pages, page_size, pages_per_slot,
                 page_dtype="", page_bytes=0):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "scratch page); got {}.".format(num_pages))
        if page_size < 1 or pages_per_slot < 1:
            raise ValueError("page_size and pages_per_slot must be "
                             ">= 1.")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.pages_per_slot = int(pages_per_slot)
        # Byte accounting for the KV-hierarchy gauges: page_dtype is
        # the storage dtype name ("" = the engine compute dtype,
        # "int8" = graftpack quantized pages) and page_bytes the HBM
        # bytes ONE physical page costs summed over every attention
        # layer (K + V + scale sidecars). Zero when the engine doesn't
        # wire it (pool used standalone in tests).
        self.page_dtype = str(page_dtype)
        self.page_bytes = int(page_bytes)
        self._cond = threading.Condition()
        # LIFO free list: recently-freed pages are re-handed first
        # (warm in whatever cache hierarchy the backend keeps).
        self._free = list(range(1, self.num_pages))
        self._refs = {}  # page id -> holder count, allocated pages only
        self._cow_copies = 0
        self._reserve_waiters = 0
        self._prefilling = 0
        self._closed = False

    @property
    def capacity(self):
        """Allocatable pages (scratch excluded)."""
        return self.num_pages - 1

    def available(self):
        with self._cond:
            return len(self._free)

    def pages_needed(self, prompt_tokens, max_new_tokens, slack=0):
        """Pages a request holds for its lifetime: one slot writes
        `prompt + max_new - 1` cache positions (the final sampled token
        is returned but never written back). `slack` adds positions the
        slot may transiently overshoot into — the speculative tick
        writes up to `spec_k` draft positions past the last committed
        token before rewinding."""
        tokens = prompt_tokens + max(int(max_new_tokens) - 1, 0) + slack
        need = -(-tokens // self.page_size)  # ceil
        if need > self.pages_per_slot:
            raise ValueError(
                "request needs {} pages but a slot addresses only {} "
                "({} tokens / page_size {}).".format(
                    need, self.pages_per_slot,
                    self.pages_per_slot * self.page_size,
                    self.page_size))
        return need

    def reserve(self, n, timeout=None):
        """Takes `n` pages off the free list, blocking until available.

        Returns the list of page ids (each at refcount 1), or None on
        timeout/close. A request for more than `capacity` pages raises
        immediately — waiting could never succeed (the deadlock the
        scheduler's submit-time validation also rejects).
        """
        n = int(n)
        if n == 0:
            return []
        if n > self.capacity:
            raise ValueError(
                "cannot reserve {} pages from a pool of {} allocatable "
                "pages.".format(n, self.capacity))
        with self._cond:
            # The waiter count only becomes observable while wait_for
            # actually releases the lock, so the gauge reads as "threads
            # currently blocked on page reservation" — live backpressure.
            self._reserve_waiters += 1
            try:
                ok = self._cond.wait_for(
                    lambda: self._closed or len(self._free) >= n,
                    timeout=timeout)
            finally:
                self._reserve_waiters -= 1
            if self._closed or not ok:
                return None
            pages = [self._free.pop() for _ in range(n)]
            for pid in pages:
                self._refs[pid] = 1
            return pages

    def share(self, page_ids):
        """Adds one holder to each already-allocated page (prefix-cache
        hit: a new request maps populated pages into its table)."""
        with self._cond:
            for pid in page_ids:
                pid = int(pid)
                if pid not in self._refs:
                    raise ValueError(
                        "cannot share unallocated page {}.".format(pid))
                self._refs[pid] += 1

    def refcount(self, page_id):
        """Current holder count for a page (0 when free)."""
        with self._cond:
            return self._refs.get(int(page_id), 0)

    def free(self, page_ids):
        """Drops one holder per page; recycles pages whose last holder
        let go and wakes blocked reservers."""
        if not page_ids:
            return
        with self._cond:
            recycled = False
            for pid in page_ids:
                pid = int(pid)
                if not 1 <= pid < self.num_pages:
                    raise ValueError(
                        "page id {} outside pool [1, {}).".format(
                            pid, self.num_pages))
                refs = self._refs.get(pid, 0)
                if refs <= 0:
                    raise ValueError(
                        "double free of page {}.".format(pid))
                if refs == 1:
                    del self._refs[pid]
                    self._free.append(pid)
                    recycled = True
                else:
                    self._refs[pid] = refs - 1
            if recycled:
                self._cond.notify_all()

    def reserve_waiters(self):
        """Threads currently blocked inside reserve() (backpressure)."""
        with self._cond:
            return self._reserve_waiters

    def squeeze(self, n):
        """Confiscates up to `n` FREE pages immediately (no blocking, a
        partial take is fine) — the chaos `pool_squeeze` primitive: a
        noisy neighbor claiming HBM that admission backpressure must
        absorb. The taken pages are ordinary refcount-1 allocations, so
        returning them is a plain free() and the leak detector treats a
        squeeze holder like any other."""
        n = int(n)
        with self._cond:
            take = min(n, len(self._free))
            pages = [self._free.pop() for _ in range(take)]
            for pid in pages:
                self._refs[pid] = 1
            return pages

    def note_cow(self, n=1):
        """Counts a copy-on-write page reconstruction (telemetry)."""
        with self._cond:
            self._cow_copies += int(n)

    def note_prefill_hold(self, n):
        """Marks `n` already-reserved pages as held by an in-flight
        (chunked) prefill — occupancy accounting only, no allocation.
        A multi-chunk prefill holds its pages for several ticks before
        its slot insert, so `pages_prefilling` splits `pages_held`
        into decoding vs still-prefilling for the SERVE_* gauges."""
        with self._cond:
            self._prefilling += int(n)

    def note_prefill_release(self, n):
        """Drops `n` pages from the prefill-hold count (the prefill
        inserted, failed, or was drained — the pages themselves move
        or free separately)."""
        with self._cond:
            self._prefilling -= int(n)
            if self._prefilling < 0:
                raise ValueError(
                    "prefill-hold underflow: released more prefilling "
                    "pages than held.")

    def pool_stats(self):
        """Point-in-time accounting: free/held/shared page counts, CoW
        copies since construction, and a holder-count histogram
        ({refcount: pages}) — the raw material for the SERVE_* gauges
        and the refcount leak detector."""
        with self._cond:
            hist = {}
            for refs in self._refs.values():
                hist[refs] = hist.get(refs, 0) + 1
            return {
                "pages_free": len(self._free),
                "pages_held": len(self._refs),
                "pages_shared": sum(1 for r in self._refs.values()
                                    if r >= 2),
                "pages_prefilling": self._prefilling,
                "cow_copies": self._cow_copies,
                "reserve_waiters": self._reserve_waiters,
                "refcount_hist": hist,
                "page_dtype": self.page_dtype,
                "kv_bytes_held": len(self._refs) * self.page_bytes,
                "kv_bytes_total": self.capacity * self.page_bytes,
            }

    def leak_report(self):
        """Pages still held, with holder counts. A drained scheduler
        (all requests complete, prefix cache cleared) must see {} here
        — anything else is a refcount leak."""
        with self._cond:
            return dict(self._refs)

    def close(self):
        """Unblocks every waiting reserve with None (shutdown path)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def page_vec(self, page_ids):
        """A full-width page-table row for `page_ids`: the reserved ids
        in logical order, scratch (0) beyond them. Fixed [pages_per_slot]
        shape keeps the insert executable monomorphic."""
        vec = np.zeros((self.pages_per_slot,), np.int32)
        vec[:len(page_ids)] = page_ids
        return vec


class HostPageTier:
    """Host-RAM second tier of the KV page hierarchy (graftpack).

    Holds page-granular KV snapshots of completed conversation turns,
    keyed by the token prefix they encode, so the NEXT turn's admission
    can promote them back with a few H2D page copies instead of
    re-prefilling the whole history. This turns the prefix cache into a
    session store that survives pool pressure: trie eviction may drop
    the device pages, the host copy persists.

    An entry is `{key: token tuple (page-aligned prefix), pages: the
    engine's host-side page pytree snapshot (numpy; per-layer K/V page
    blocks + scale sidecars in int8 mode), n_pages, digest}`. The
    digest is `checkpoint.tree_digest` over the snapshot at demote
    time; promote recomputes it and a mismatch is a typed
    `HostTierCorrupt` fault — the entry is dropped and admission falls
    back to re-prefill, never serving corrupt pages.

    Budgeted in PAGES with LRU eviction (a host tier exists to be much
    larger than HBM, but smoke rigs still need determinism). All
    host-side python, thread-safe; the device is only ever touched by
    the engine's fixed-shape promote executable.
    """

    def __init__(self, max_pages, page_size):
        if max_pages < 1:
            raise ValueError("max_pages must be >= 1; got {}.".format(
                max_pages))
        self.max_pages = int(max_pages)
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        self._entries = {}   # key tuple -> entry dict
        self._clock = 0
        self.demotes = 0
        self.promotes = 0
        self.digest_failures = 0
        self.evictions = 0

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def held_pages(self):
        with self._lock:
            return sum(e["n_pages"] for e in self._entries.values())

    def contains(self, tokens):
        """True when an entry for exactly this page-aligned prefix
        exists (cheap pre-snapshot dedup check)."""
        with self._lock:
            return tuple(tokens) in self._entries

    def put(self, tokens, pages, n_pages, digest):
        """Demotes a snapshot: `tokens` is the page-aligned token
        prefix the pages encode (len == n_pages * page_size), `pages`
        the host pytree, `digest` its tree_digest stamp. Evicts LRU
        entries to stay under the page budget; an oversized snapshot
        is refused (False) rather than thrashing the whole tier."""
        key = tuple(int(t) for t in tokens)
        if len(key) != n_pages * self.page_size:
            raise ValueError(
                "demote key must be page-aligned: {} tokens vs {} "
                "pages of {}.".format(len(key), n_pages,
                                      self.page_size))
        if n_pages > self.max_pages:
            return False
        with self._lock:
            held = sum(e["n_pages"] for e in self._entries.values())
            if key in self._entries:
                held -= self._entries[key]["n_pages"]
            while held + n_pages > self.max_pages and self._entries:
                lru = min(self._entries,
                          key=lambda k: self._entries[k]["stamp"])
                held -= self._entries[lru]["n_pages"]
                del self._entries[lru]
                self.evictions += 1
            self._clock += 1
            self._entries[key] = {"pages": pages, "n_pages": n_pages,
                                  "digest": digest,
                                  "stamp": self._clock}
            self.demotes += 1
            return True

    def probe(self, tokens):
        """Longest page-aligned prefix of `tokens` with a host entry,
        in TOKENS (0 = none). Side-effect-free and cheap — one dict
        probe per page boundary, longest first — so admission can rank
        by it like the trie's probe."""
        limit = (len(tokens) - 1) // self.page_size
        key = tuple(int(t) for t in tokens[:limit * self.page_size])
        with self._lock:
            for n in range(limit, 0, -1):
                if key[:n * self.page_size] in self._entries:
                    return n * self.page_size
        return 0

    def get(self, tokens, n_pages):
        """The entry for exactly `tokens[:n_pages * page_size]`, LRU-
        refreshed, or None. Digest verification is the CALLER's step
        (scheduler promote) so the failure is typed and counted there."""
        key = tuple(int(t) for t in tokens[:n_pages * self.page_size])
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._clock += 1
                entry["stamp"] = self._clock
            return entry

    def drop(self, tokens, n_pages):
        """Removes one entry (digest mismatch / explicit invalidation)."""
        key = tuple(int(t) for t in tokens[:n_pages * self.page_size])
        with self._lock:
            self._entries.pop(key, None)

    def note_promote(self):
        with self._lock:
            self.promotes += 1

    def note_digest_failure(self):
        with self._lock:
            self.digest_failures += 1

    def clear(self):
        with self._lock:
            self._entries.clear()

    def reset_stats(self):
        with self._lock:
            self.demotes = 0
            self.promotes = 0
            self.digest_failures = 0
            self.evictions = 0

    def stats(self):
        with self._lock:
            return {
                "entries": len(self._entries),
                "pages": sum(e["n_pages"]
                             for e in self._entries.values()),
                "max_pages": self.max_pages,
                "demotes": self.demotes,
                "promotes": self.promotes,
                "digest_failures": self.digest_failures,
                "evictions": self.evictions,
            }


__all__ = ["PagePool", "HostPageTier"]
