"""graftlens loadgen: open-arrival traffic against a live Scheduler.

Closed-loop drivers (smoke.py's run_serve) submit the next request when
the previous one finishes, so they can never observe queueing collapse:
the system sets its own arrival rate. This generator is OPEN-LOOP — a
fixed seed draws an arrival schedule (Poisson, or bursty Gamma renewal
with CV^2 = `burstiness`), a prompt-length mix, a shared-prefix ratio,
and per-request decode budgets, then submits each request at its
scheduled wall time regardless of completions. Latency under load is
then a property of the serving stack, not of the driver.

Goodput is the serving SLO currency: the fraction of OFFERED requests
that completed AND met both targets (TTFT <= --slo-ttft, TPOT <=
--slo-tpot, TPOT = (latency - ttft) / (tokens - 1)). Shed or failed
requests count against goodput by construction.

The module is also the CI `serve-trace-smoke` driver: run with
`CLOUD_TPU_REQTRACE=1` it produces the reqtrace JSONL that
`monitoring/collect.py --serve` rolls into the per-request waterfall +
`serve_report.json`, and `BENCH_SERVE_LOAD=1` (bench.py) records
offered load vs. achieved goodput at several arrival rates.

Usage (CPU-friendly):

    JAX_PLATFORMS=cpu CLOUD_TPU_REQTRACE=1 \\
        python -m cloud_tpu.serving.loadgen \\
        --requests 20 --rate 8 --out-dir /tmp/lens
"""

import argparse
import dataclasses
import json
import os
import queue
import time

import numpy as np

from cloud_tpu.serving.faults import fault_kind


@dataclasses.dataclass
class LoadSpec:
    """One open-arrival run. All randomness flows from `seed`, so a
    spec is a complete, reproducible description of the traffic."""
    rate: float                     # mean arrivals per second
    n_requests: int = 20
    process: str = "poisson"        # "poisson" | "bursty"
    burstiness: float = 4.0         # Gamma CV^2 (1.0 == poisson)
    # Prompt-length mix: (length, weight) pairs, normalized.
    prompt_buckets: tuple = ((6, 0.4), (12, 0.35), (24, 0.25))
    max_new_lo: int = 2
    max_new_hi: int = 8             # inclusive
    shared_prefix_ratio: float = 0.0
    shared_prefix_len: int = 16
    seed: int = 0
    submit_timeout: float = 0.05    # then shed (queue.Full -> rejected)

    def validate(self):
        if self.rate <= 0:
            raise ValueError("rate must be > 0.")
        if self.n_requests < 1:
            raise ValueError("n_requests must be >= 1.")
        if self.process not in ("poisson", "bursty"):
            raise ValueError("process must be poisson|bursty; got "
                             "{!r}.".format(self.process))
        if self.burstiness <= 0:
            raise ValueError("burstiness must be > 0.")
        if not 0.0 <= self.shared_prefix_ratio <= 1.0:
            raise ValueError("shared_prefix_ratio must be in [0, 1].")


def build_arrivals(spec):
    """Arrival times (seconds from run start), shape [n_requests].

    poisson: exponential inter-arrivals, mean 1/rate. bursty: Gamma
    inter-arrivals with shape 1/burstiness and scale burstiness/rate —
    same mean 1/rate, CV^2 = burstiness, so load comes in clumps while
    the offered rate stays comparable across processes.
    """
    spec.validate()
    rng = np.random.default_rng(spec.seed)
    if spec.process == "poisson":
        gaps = rng.exponential(1.0 / spec.rate, spec.n_requests)
    else:
        gaps = rng.gamma(1.0 / spec.burstiness,
                         spec.burstiness / spec.rate, spec.n_requests)
    return np.cumsum(gaps)


def build_requests(spec, vocab_size, max_seq_len):
    """Deterministic request list for `spec`. Token ids stay in
    [2, vocab); shared-prefix requests extend one common root (the
    radix-cache hit population) and everything fits prompt + max_new
    <= max_seq_len."""
    from cloud_tpu.serving.scheduler import ServeRequest

    spec.validate()
    rng = np.random.default_rng(spec.seed + 1)
    lengths = [int(length) for length, _ in spec.prompt_buckets]
    weights = np.asarray([w for _, w in spec.prompt_buckets], float)
    weights = weights / weights.sum()
    hi = max(2, vocab_size)
    root = rng.integers(2, hi, (spec.shared_prefix_len,)).tolist()
    requests = []
    for _ in range(spec.n_requests):
        length = int(rng.choice(lengths, p=weights))
        max_new = int(rng.integers(spec.max_new_lo,
                                   spec.max_new_hi + 1))
        length = min(length, max_seq_len - max_new)
        shared = (rng.random() < spec.shared_prefix_ratio
                  and length > spec.shared_prefix_len)
        if shared:
            tail = rng.integers(2, hi, (length
                                        - spec.shared_prefix_len,))
            prompt = root + tail.tolist()
        else:
            prompt = rng.integers(2, hi, (length,)).tolist()
        requests.append(ServeRequest(
            prompt=[int(t) for t in prompt],
            max_new_tokens=max_new, temperature=0.0,
            rng_seed=int(rng.integers(0, 2**31 - 1))))
    return requests


def _percentiles(values):
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return {"count": 0, "p50": None, "p95": None, "p99": None,
                "mean": None}
    arr = np.asarray(vals, float)
    return {
        "count": len(vals),
        "p50": float(np.percentile(arr, 50)),
        "p95": float(np.percentile(arr, 95)),
        "p99": float(np.percentile(arr, 99)),
        "mean": float(arr.mean()),
    }


def run_load(scheduler, spec, slo_ttft=None, slo_tpot=None,
             result_timeout=300.0):
    """Drives one open-arrival run against a started, warmed Scheduler.

    Returns the run report dict (format cloud_tpu.loadgen.v1): offered /
    completed / rejected / failed / shed counts (shed = refused by the
    SLO admission gate, a typed ServeShed), offered vs. achieved rps,
    TTFT / TPOT / latency percentiles, goodput against the SLOs, and a
    per-request row list (the collector's cross-check against the
    reqtrace waterfall).
    """
    arrivals = build_arrivals(spec)
    requests = build_requests(spec, scheduler.engine.model.vocab_size,
                              scheduler.engine.max_seq_len)
    inflight = []
    t0 = time.monotonic()
    for request, t_arr in zip(requests, arrivals):
        delay = t0 + float(t_arr) - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t_sub = time.monotonic() - t0
        try:
            future = scheduler.submit(request,
                                      timeout=spec.submit_timeout)
        except queue.Full:
            inflight.append((request, t_sub, None))
            continue
        inflight.append((request, t_sub, future))

    rows = []
    completed = rejected = failed = shed = 0
    t_last_done = t0
    for request, t_sub, future in inflight:
        row = {
            "submit_s": round(t_sub, 6),
            "prompt_len": len(request.prompt),
            "max_new": request.max_new_tokens,
        }
        if future is None:
            rejected += 1
            row["status"] = "rejected"
            rows.append(row)
            continue
        try:
            result = future.result(timeout=result_timeout)
        except BaseException as exc:  # noqa: BLE001
            if fault_kind(exc) == "shed":
                shed += 1
                row["status"] = "shed"
                row["reason"] = getattr(exc, "reason", None)
            else:
                failed += 1
                row["status"] = "failed"
            row["error"] = "{}: {}".format(type(exc).__name__,
                                           str(exc)[:200])
            rows.append(row)
            continue
        completed += 1
        t_last_done = max(t_last_done, time.monotonic())
        n = request.max_new_tokens
        tpot = ((result.latency_s - result.ttft_s) / (n - 1)
                if n > 1 else None)
        row.update(status="complete",
                   ttft_s=round(result.ttft_s, 6),
                   latency_s=round(result.latency_s, 6),
                   tpot_s=None if tpot is None else round(tpot, 6),
                   prefix_len=int(result.prefix_len),
                   hit=bool(result.prefix_len > 0))
        row["good"] = bool(
            (slo_ttft is None or result.ttft_s <= slo_ttft)
            and (slo_tpot is None or tpot is None or tpot <= slo_tpot))
        rows.append(row)

    wall = max(t_last_done - t0, 1e-9)
    offered_span = max(float(arrivals[-1]), 1e-9)
    good = sum(1 for r in rows if r.get("good"))
    done_rows = [r for r in rows if r["status"] == "complete"]
    return {
        "format": "cloud_tpu.loadgen.v1",
        "spec": {
            "rate": spec.rate,
            "n_requests": spec.n_requests,
            "process": spec.process,
            "burstiness": spec.burstiness,
            "prompt_buckets": [list(b) for b in spec.prompt_buckets],
            "max_new": [spec.max_new_lo, spec.max_new_hi],
            "shared_prefix_ratio": spec.shared_prefix_ratio,
            "shared_prefix_len": spec.shared_prefix_len,
            "seed": spec.seed,
        },
        "offered": len(rows),
        "completed": completed,
        "rejected": rejected,
        "failed": failed,
        "shed": shed,
        "offered_rps": len(rows) / offered_span,
        "achieved_rps": completed / wall,
        "duration_s": wall,
        "slo": {"ttft_s": slo_ttft, "tpot_s": slo_tpot},
        "goodput": good / max(len(rows), 1),
        "ttft": _percentiles([r.get("ttft_s") for r in done_rows]),
        "tpot": _percentiles([r.get("tpot_s") for r in done_rows]),
        "latency": _percentiles([r.get("latency_s")
                                 for r in done_rows]),
        "hit_rate": (sum(1 for r in done_rows if r.get("hit"))
                     / max(len(done_rows), 1)),
        "per_request": rows,
    }


def _build_scheduler(args):
    import jax
    import jax.numpy as jnp

    from cloud_tpu.serving.scheduler import Scheduler
    from cloud_tpu.serving.smoke import build_model

    model = build_model(num_layers=args.layers)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    pages_per_slot = model.max_seq_len // args.page_size
    return Scheduler(model, params, slots=args.slots,
                     page_size=args.page_size,
                     num_pages=(args.slots + 4) * pages_per_slot + 1,
                     admission_window=args.slots,
                     strict_no_retrace=False)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="open-arrival load generator for graftserve")
    parser.add_argument("--rate", type=float, action="append",
                        help="arrivals/sec; repeat for a load sweep "
                        "(default: one run at 8.0)")
    parser.add_argument("--requests", type=int, default=20)
    parser.add_argument("--process", default="poisson",
                        choices=("poisson", "bursty"))
    parser.add_argument("--burstiness", type=float, default=4.0)
    parser.add_argument("--shared-prefix-ratio", type=float,
                        default=0.5)
    parser.add_argument("--shared-prefix-len", type=int, default=16)
    parser.add_argument("--slo-ttft", type=float, default=None)
    parser.add_argument("--slo-tpot", type=float, default=None)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--page-size", type=int, default=16)
    parser.add_argument("--layers", type=int, default=6,
                        help="model depth (2 keeps CI fast)")
    parser.add_argument("--out-dir", default="loadgen-out")
    args = parser.parse_args(argv)

    os.makedirs(args.out_dir, exist_ok=True)
    from cloud_tpu.serving import reqtrace
    if reqtrace.env_enabled() and reqtrace.get() is None:
        # Default the trace next to the report so one --out-dir is the
        # whole artifact (CLOUD_TPU_REQTRACE_DIR still wins).
        os.environ.setdefault("CLOUD_TPU_REQTRACE_DIR", args.out_dir)

    scheduler = _build_scheduler(args)
    scheduler.start()
    rates = args.rate or [8.0]
    specs = [LoadSpec(rate=rate, n_requests=args.requests,
                      process=args.process,
                      burstiness=args.burstiness,
                      shared_prefix_ratio=args.shared_prefix_ratio,
                      shared_prefix_len=args.shared_prefix_len,
                      seed=args.seed + i)
             for i, rate in enumerate(rates)]
    runs = []
    try:
        all_requests = []
        for spec in specs:
            all_requests.extend(build_requests(
                spec, scheduler.engine.model.vocab_size,
                scheduler.engine.max_seq_len))
        buckets = sorted({scheduler._bucket(r) for r in all_requests})
        print("[loadgen] warmup over buckets {}".format(buckets))
        scheduler.warmup(buckets,
                         sampling_configs=[(("temperature", 0.0),)])
        for spec in specs:
            print("[loadgen] {} x{} @ {:.3g} req/s".format(
                spec.process, spec.n_requests, spec.rate))
            run = run_load(scheduler, spec, slo_ttft=args.slo_ttft,
                           slo_tpot=args.slo_tpot)
            print("[loadgen]   offered {:.3g} rps, achieved {:.3g} "
                  "rps, goodput {:.3f}, ttft p95 {}".format(
                      run["offered_rps"], run["achieved_rps"],
                      run["goodput"], run["ttft"]["p95"]))
            runs.append(run)
        stats = scheduler.stats()
    finally:
        scheduler.close()
        tracer = reqtrace.get()
        if tracer is not None:
            tracer.flush()

    report = {
        "format": "cloud_tpu.loadgen_sweep.v1",
        "runs": runs,
        "scheduler_stats": {
            "queue_wait": stats["queue_wait"],
            "reserve_wait": stats["reserve_wait"],
            "ttft": stats["ttft"],
            "prefix_hit_rate": stats["prefix_hit_rate"],
        },
    }
    out_path = os.path.join(args.out_dir, "loadgen_report.json")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    print("[loadgen] wrote {}".format(out_path))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
