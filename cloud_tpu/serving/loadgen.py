"""graftlens loadgen: open-arrival traffic against a live Scheduler.

Closed-loop drivers (smoke.py's run_serve) submit the next request when
the previous one finishes, so they can never observe queueing collapse:
the system sets its own arrival rate. This generator is OPEN-LOOP — a
fixed seed draws an arrival schedule (Poisson, or bursty Gamma renewal
with CV^2 = `burstiness`), a prompt-length mix, a shared-prefix ratio,
and per-request decode budgets, then submits each request at its
scheduled wall time regardless of completions. Latency under load is
then a property of the serving stack, not of the driver.

Goodput is the serving SLO currency: the fraction of OFFERED requests
that completed AND met both targets (TTFT <= --slo-ttft, TPOT <=
--slo-tpot, TPOT = (latency - ttft) / (tokens - 1)). Shed or failed
requests count against goodput by construction.

The module is also the CI `serve-trace-smoke` driver: run with
`CLOUD_TPU_REQTRACE=1` it produces the reqtrace JSONL that
`monitoring/collect.py --serve` rolls into the per-request waterfall +
`serve_report.json`, and `BENCH_SERVE_LOAD=1` (bench.py) records
offered load vs. achieved goodput at several arrival rates.

Usage (CPU-friendly):

    JAX_PLATFORMS=cpu CLOUD_TPU_REQTRACE=1 \\
        python -m cloud_tpu.serving.loadgen \\
        --requests 20 --rate 8 --out-dir /tmp/lens
"""

import argparse
import dataclasses
import json
import os
import queue
import threading
import time

import numpy as np

from cloud_tpu.serving.faults import fault_kind


@dataclasses.dataclass
class LoadSpec:
    """One open-arrival run. All randomness flows from `seed`, so a
    spec is a complete, reproducible description of the traffic."""
    rate: float                     # mean arrivals per second
    n_requests: int = 20
    process: str = "poisson"        # "poisson" | "bursty"
    burstiness: float = 4.0         # Gamma CV^2 (1.0 == poisson)
    # Prompt-length mix: (length, weight) pairs, normalized.
    prompt_buckets: tuple = ((6, 0.4), (12, 0.35), (24, 0.25))
    max_new_lo: int = 2
    max_new_hi: int = 8             # inclusive
    shared_prefix_ratio: float = 0.0
    shared_prefix_len: int = 16
    seed: int = 0
    submit_timeout: float = 0.05    # then shed (queue.Full -> rejected)

    def validate(self):
        if self.rate <= 0:
            raise ValueError("rate must be > 0.")
        if self.n_requests < 1:
            raise ValueError("n_requests must be >= 1.")
        if self.process not in ("poisson", "bursty"):
            raise ValueError("process must be poisson|bursty; got "
                             "{!r}.".format(self.process))
        if self.burstiness <= 0:
            raise ValueError("burstiness must be > 0.")
        if not 0.0 <= self.shared_prefix_ratio <= 1.0:
            raise ValueError("shared_prefix_ratio must be in [0, 1].")


def build_arrivals(spec):
    """Arrival times (seconds from run start), shape [n_requests].

    poisson: exponential inter-arrivals, mean 1/rate. bursty: Gamma
    inter-arrivals with shape 1/burstiness and scale burstiness/rate —
    same mean 1/rate, CV^2 = burstiness, so load comes in clumps while
    the offered rate stays comparable across processes.
    """
    spec.validate()
    rng = np.random.default_rng(spec.seed)
    if spec.process == "poisson":
        gaps = rng.exponential(1.0 / spec.rate, spec.n_requests)
    else:
        gaps = rng.gamma(1.0 / spec.burstiness,
                         spec.burstiness / spec.rate, spec.n_requests)
    return np.cumsum(gaps)


def build_requests(spec, vocab_size, max_seq_len):
    """Deterministic request list for `spec`. Token ids stay in
    [2, vocab); shared-prefix requests extend one common root (the
    radix-cache hit population) and everything fits prompt + max_new
    <= max_seq_len."""
    from cloud_tpu.serving.scheduler import ServeRequest

    spec.validate()
    rng = np.random.default_rng(spec.seed + 1)
    lengths = [int(length) for length, _ in spec.prompt_buckets]
    weights = np.asarray([w for _, w in spec.prompt_buckets], float)
    weights = weights / weights.sum()
    hi = max(2, vocab_size)
    root = rng.integers(2, hi, (spec.shared_prefix_len,)).tolist()
    requests = []
    for _ in range(spec.n_requests):
        length = int(rng.choice(lengths, p=weights))
        max_new = int(rng.integers(spec.max_new_lo,
                                   spec.max_new_hi + 1))
        length = min(length, max_seq_len - max_new)
        shared = (rng.random() < spec.shared_prefix_ratio
                  and length > spec.shared_prefix_len)
        if shared:
            tail = rng.integers(2, hi, (length
                                        - spec.shared_prefix_len,))
            prompt = root + tail.tolist()
        else:
            prompt = rng.integers(2, hi, (length,)).tolist()
        requests.append(ServeRequest(
            prompt=[int(t) for t in prompt],
            max_new_tokens=max_new, temperature=0.0,
            rng_seed=int(rng.integers(0, 2**31 - 1))))
    return requests


def _percentiles(values):
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return {"count": 0, "p50": None, "p95": None, "p99": None,
                "mean": None}
    arr = np.asarray(vals, float)
    return {
        "count": len(vals),
        "p50": float(np.percentile(arr, 50)),
        "p95": float(np.percentile(arr, 95)),
        "p99": float(np.percentile(arr, 99)),
        "mean": float(arr.mean()),
    }


def _run_open_loop(scheduler, requests, arrivals, submit_timeout,
                   slo_ttft, slo_tpot, result_timeout, tags=None,
                   keep_tokens=False):
    """Open-loop core shared by every arrival scenario: submit each
    request at its scheduled offset from run start regardless of
    completions, then harvest every future. `tags` (optional, parallel
    to `requests`) is a dict merged into each per-request row — how the
    diurnal scenario stamps rows with their segment. Returns
    (rows, counts, wall_s)."""
    inflight = []
    t0 = time.monotonic()
    for i, (request, t_arr) in enumerate(zip(requests, arrivals)):
        delay = t0 + float(t_arr) - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t_sub = time.monotonic() - t0
        try:
            future = scheduler.submit(request, timeout=submit_timeout)
        except queue.Full:
            future = None
        inflight.append((i, request, t_sub, future))

    rows = []
    completed = rejected = failed = shed = 0
    t_last_done = t0
    for i, request, t_sub, future in inflight:
        row = {
            "submit_s": round(t_sub, 6),
            "prompt_len": len(request.prompt),
            "max_new": request.max_new_tokens,
        }
        if tags is not None:
            row.update(tags[i])
        if future is None:
            rejected += 1
            row["status"] = "rejected"
            rows.append(row)
            continue
        try:
            result = future.result(timeout=result_timeout)
        except BaseException as exc:  # noqa: BLE001
            if fault_kind(exc) == "shed":
                shed += 1
                row["status"] = "shed"
                row["reason"] = getattr(exc, "reason", None)
            else:
                failed += 1
                row["status"] = "failed"
            row["error"] = "{}: {}".format(type(exc).__name__,
                                           str(exc)[:200])
            rows.append(row)
            continue
        completed += 1
        t_last_done = max(t_last_done, time.monotonic())
        n = request.max_new_tokens
        tpot = ((result.latency_s - result.ttft_s) / (n - 1)
                if n > 1 else None)
        row.update(status="complete",
                   ttft_s=round(result.ttft_s, 6),
                   latency_s=round(result.latency_s, 6),
                   tpot_s=None if tpot is None else round(tpot, 6),
                   prefix_len=int(result.prefix_len),
                   hit=bool(result.prefix_len > 0))
        if keep_tokens:
            row["tokens"] = [int(t) for t in result.tokens]
        row["good"] = bool(
            (slo_ttft is None or result.ttft_s <= slo_ttft)
            and (slo_tpot is None or tpot is None or tpot <= slo_tpot))
        rows.append(row)

    wall = max(t_last_done - t0, 1e-9)
    counts = {"completed": completed, "rejected": rejected,
              "failed": failed, "shed": shed}
    return rows, counts, wall


def run_load(scheduler, spec, slo_ttft=None, slo_tpot=None,
             result_timeout=300.0):
    """Drives one open-arrival run against a started, warmed Scheduler.

    Returns the run report dict (format cloud_tpu.loadgen.v1): offered /
    completed / rejected / failed / shed counts (shed = refused by the
    SLO admission gate, a typed ServeShed), offered vs. achieved rps,
    TTFT / TPOT / latency percentiles, goodput against the SLOs, and a
    per-request row list (the collector's cross-check against the
    reqtrace waterfall).
    """
    arrivals = build_arrivals(spec)
    requests = build_requests(spec, scheduler.engine.model.vocab_size,
                              scheduler.engine.max_seq_len)
    rows, counts, wall = _run_open_loop(
        scheduler, requests, arrivals, spec.submit_timeout,
        slo_ttft, slo_tpot, result_timeout)
    completed = counts["completed"]
    rejected = counts["rejected"]
    failed = counts["failed"]
    shed = counts["shed"]
    offered_span = max(float(arrivals[-1]), 1e-9)
    good = sum(1 for r in rows if r.get("good"))
    done_rows = [r for r in rows if r["status"] == "complete"]
    return {
        "format": "cloud_tpu.loadgen.v1",
        "spec": {
            "rate": spec.rate,
            "n_requests": spec.n_requests,
            "process": spec.process,
            "burstiness": spec.burstiness,
            "prompt_buckets": [list(b) for b in spec.prompt_buckets],
            "max_new": [spec.max_new_lo, spec.max_new_hi],
            "shared_prefix_ratio": spec.shared_prefix_ratio,
            "shared_prefix_len": spec.shared_prefix_len,
            "seed": spec.seed,
        },
        "offered": len(rows),
        "completed": completed,
        "rejected": rejected,
        "failed": failed,
        "shed": shed,
        "offered_rps": len(rows) / offered_span,
        "achieved_rps": completed / wall,
        "duration_s": wall,
        "slo": {"ttft_s": slo_ttft, "tpot_s": slo_tpot},
        "goodput": good / max(len(rows), 1),
        "ttft": _percentiles([r.get("ttft_s") for r in done_rows]),
        "tpot": _percentiles([r.get("tpot_s") for r in done_rows]),
        "latency": _percentiles([r.get("latency_s")
                                 for r in done_rows]),
        "hit_rate": (sum(1 for r in done_rows if r.get("hit"))
                     / max(len(done_rows), 1)),
        "per_request": rows,
    }


@dataclasses.dataclass
class DiurnalSpec:
    """Sinusoidal-ramp offered rate (graftflex's A/B workload): the run
    is `segments` back-to-back windows of `segment_s` seconds whose
    offered rate traces half a diurnal cycle — starts at `rate_lo`,
    peaks at `rate_hi` mid-run, and ramps back down. Within each
    segment arrivals come from the existing Poisson/bursty machinery at
    that segment's rate, so the only new ingredient is the envelope.
    The ramp-up exercises grow resizes, the ramp-down shrink resizes,
    and the per-segment goodput-vs-offered curve is the autoscale-vs-
    fixed comparison surface. All randomness flows from `seed`."""
    rate_lo: float = 2.0
    rate_hi: float = 16.0
    segments: int = 6
    segment_s: float = 2.0
    process: str = "poisson"
    burstiness: float = 4.0
    prompt_buckets: tuple = ((6, 0.4), (12, 0.35), (24, 0.25))
    max_new_lo: int = 2
    max_new_hi: int = 8             # inclusive
    shared_prefix_ratio: float = 0.0
    shared_prefix_len: int = 16
    seed: int = 0
    submit_timeout: float = 0.05

    def validate(self):
        if not 0 < self.rate_lo <= self.rate_hi:
            raise ValueError("need 0 < rate_lo <= rate_hi.")
        if self.segments < 2:
            raise ValueError("segments must be >= 2.")
        if self.segment_s <= 0:
            raise ValueError("segment_s must be > 0.")

    def segment_rates(self):
        """Offered rate per segment: raised-cosine from rate_lo up to
        rate_hi and back — segment 0 sits at the trough, the midpoint
        at the crest."""
        n = self.segments
        return [self.rate_lo + (self.rate_hi - self.rate_lo) * 0.5
                * (1.0 - float(np.cos(2.0 * np.pi * k / n)))
                for k in range(n)]


def build_diurnal(spec, vocab_size, max_seq_len):
    """The complete diurnal traffic for `spec`, sorted by arrival
    time: a list of (arrival_s, segment, request) entries. Each
    segment draws its own arrival schedule and request population from
    distinct seed streams, so two schedulers fed the same spec (an
    autoscale-vs-fixed A/B) replay identical traffic. A low-rate
    segment's tail can spill past its window; the merge-sort hands the
    submit loop one monotonic timeline."""
    spec.validate()
    entries = []
    for k, rate in enumerate(spec.segment_rates()):
        seg_spec = LoadSpec(
            rate=rate,
            n_requests=max(1, int(round(rate * spec.segment_s))),
            process=spec.process, burstiness=spec.burstiness,
            prompt_buckets=spec.prompt_buckets,
            max_new_lo=spec.max_new_lo, max_new_hi=spec.max_new_hi,
            shared_prefix_ratio=spec.shared_prefix_ratio,
            shared_prefix_len=spec.shared_prefix_len,
            seed=spec.seed + 101 * k + 1,
            submit_timeout=spec.submit_timeout)
        arrivals = build_arrivals(seg_spec) + k * spec.segment_s
        requests = build_requests(seg_spec, vocab_size, max_seq_len)
        for t_arr, request in zip(arrivals, requests):
            entries.append((float(t_arr), k, request))
    entries.sort(key=lambda e: e[0])
    return entries


def run_diurnal(scheduler, spec, slo_ttft=None, slo_tpot=None,
                result_timeout=300.0, keep_tokens=False):
    """Drives one sinusoidal-ramp run against a started, warmed
    Scheduler.

    Every per-request row is stamped with its segment and its index
    `i` into the deterministic `build_diurnal` population (how an A/B
    harness lines rows up against a solo-generate oracle);
    `keep_tokens=True` additionally records each completed request's
    token ids for bit-identity checks. Returns the run report (format
    cloud_tpu.loadgen_diurnal.v1): the overall counts/goodput/
    percentiles of run_load plus `offered_curve` — per-segment offered
    rate vs goodput vs TTFT — and `worst_ttft_p99`, the worst
    per-segment TTFT p99 (the "equal worst-case p99" side of the
    ROADMAP autoscaling gate)."""
    entries = build_diurnal(spec, scheduler.engine.model.vocab_size,
                            scheduler.engine.max_seq_len)
    rates = spec.segment_rates()
    rows, counts, wall = _run_open_loop(
        scheduler, [e[2] for e in entries], [e[0] for e in entries],
        spec.submit_timeout, slo_ttft, slo_tpot, result_timeout,
        tags=[{"segment": seg, "i": i}
              for i, (_, seg, _) in enumerate(entries)],
        keep_tokens=keep_tokens)

    curve = []
    for k, rate in enumerate(rates):
        seg_rows = [r for r in rows if r["segment"] == k]
        seg_done = [r for r in seg_rows if r["status"] == "complete"]
        good = sum(1 for r in seg_rows if r.get("good"))
        curve.append({
            "segment": k,
            "offered_rate": rate,
            "offered": len(seg_rows),
            "completed": len(seg_done),
            "good": good,
            "goodput": good / max(len(seg_rows), 1),
            "ttft": _percentiles([r.get("ttft_s") for r in seg_done]),
        })
    good = sum(1 for r in rows if r.get("good"))
    done_rows = [r for r in rows if r["status"] == "complete"]
    worst_p99 = [c["ttft"]["p99"] for c in curve
                 if c["ttft"]["p99"] is not None]
    return {
        "format": "cloud_tpu.loadgen_diurnal.v1",
        "spec": {
            "rate_lo": spec.rate_lo,
            "rate_hi": spec.rate_hi,
            "segments": spec.segments,
            "segment_s": spec.segment_s,
            "segment_rates": rates,
            "process": spec.process,
            "burstiness": spec.burstiness,
            "prompt_buckets": [list(b) for b in spec.prompt_buckets],
            "max_new": [spec.max_new_lo, spec.max_new_hi],
            "shared_prefix_ratio": spec.shared_prefix_ratio,
            "shared_prefix_len": spec.shared_prefix_len,
            "seed": spec.seed,
        },
        "offered": len(rows),
        "completed": counts["completed"],
        "rejected": counts["rejected"],
        "failed": counts["failed"],
        "shed": counts["shed"],
        "duration_s": wall,
        "slo": {"ttft_s": slo_ttft, "tpot_s": slo_tpot},
        "good": good,
        "goodput": good / max(len(rows), 1),
        "worst_ttft_p99": max(worst_p99) if worst_p99 else None,
        "ttft": _percentiles([r.get("ttft_s") for r in done_rows]),
        "tpot": _percentiles([r.get("tpot_s") for r in done_rows]),
        "latency": _percentiles([r.get("latency_s")
                                 for r in done_rows]),
        "offered_curve": curve,
        "per_request": rows,
    }


@dataclasses.dataclass
class ConversationSpec:
    """Multi-turn conversation traffic (graftpack's host-tier
    workload): N concurrent sessions, each a closed loop of T turns —
    turn t's prompt is the FULL history (turn t-1's prompt +
    continuation) plus `user_tokens` fresh tokens, submitted after a
    `think_time` gap. Between a turn's completion and the next turn's
    arrival the session's KV pages are idle — exactly the window the
    host tier demotes into, and the trie LRU evicts under pressure.
    All randomness flows from `seed`."""
    n_sessions: int = 4
    n_turns: int = 3
    user_tokens: int = 8
    max_new_lo: int = 4
    max_new_hi: int = 8             # inclusive
    think_time: float = 0.05
    seed: int = 0

    def validate(self):
        if self.n_sessions < 1 or self.n_turns < 1:
            raise ValueError("n_sessions and n_turns must be >= 1.")
        if self.user_tokens < 1:
            raise ValueError("user_tokens must be >= 1.")
        if self.think_time < 0:
            raise ValueError("think_time must be >= 0.")


def run_conversations(scheduler, spec, result_timeout=300.0):
    """Drives `spec.n_sessions` concurrent multi-turn conversations.

    Each session is closed-loop (a user cannot send turn t+1 before
    reading turn t) but sessions overlap, so resident-page pressure and
    trie eviction are real. A session ends early when the growing
    history no longer fits max_seq_len. Returns the run report
    (format cloud_tpu.loadgen_conv.v1): per-turn rows with
    session/turn/prompt_len/ttft/prefix_len, plus TTFT percentiles
    split first-turn vs follow-up — the follow-up split is the number
    the host tier exists to keep near the cache-hit floor after
    eviction."""
    from cloud_tpu.serving.scheduler import ServeRequest

    spec.validate()
    max_seq_len = scheduler.engine.max_seq_len
    vocab = scheduler.engine.model.vocab_size
    hi = max(3, vocab)
    rows_lock = threading.Lock()
    rows = []

    def session(idx):
        rng = np.random.default_rng(spec.seed + 17 * idx)
        history = []
        for turn in range(spec.n_turns):
            fresh = rng.integers(2, hi, (spec.user_tokens,)).tolist()
            prompt = history + [int(t) for t in fresh]
            max_new = int(rng.integers(spec.max_new_lo,
                                       spec.max_new_hi + 1))
            if len(prompt) + max_new > max_seq_len:
                return  # history outgrew the context window
            request = ServeRequest(prompt=prompt,
                                   max_new_tokens=max_new,
                                   temperature=0.0,
                                   rng_seed=int(rng.integers(
                                       0, 2**31 - 1)))
            row = {"session": idx, "turn": turn,
                   "prompt_len": len(prompt), "max_new": max_new}
            try:
                result = scheduler.submit(request, timeout=30).result(
                    timeout=result_timeout)
            except BaseException as exc:  # noqa: BLE001
                row["status"] = ("shed" if fault_kind(exc) == "shed"
                                 else "failed")
                row["error"] = "{}: {}".format(type(exc).__name__,
                                               str(exc)[:200])
                with rows_lock:
                    rows.append(row)
                return
            row.update(status="complete",
                       ttft_s=round(result.ttft_s, 6),
                       latency_s=round(result.latency_s, 6),
                       prefix_len=int(result.prefix_len))
            with rows_lock:
                rows.append(row)
            history = [int(t) for t in result.tokens]
            if spec.think_time:
                time.sleep(spec.think_time)

    threads = [threading.Thread(target=session, args=(i,), daemon=True)
               for i in range(spec.n_sessions)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=result_timeout)
    wall = max(time.monotonic() - t0, 1e-9)
    rows.sort(key=lambda r: (r["session"], r["turn"]))
    done = [r for r in rows if r["status"] == "complete"]
    first = [r["ttft_s"] for r in done if r["turn"] == 0]
    later = [r["ttft_s"] for r in done if r["turn"] > 0]
    return {
        "format": "cloud_tpu.loadgen_conv.v1",
        "spec": {
            "n_sessions": spec.n_sessions,
            "n_turns": spec.n_turns,
            "user_tokens": spec.user_tokens,
            "max_new": [spec.max_new_lo, spec.max_new_hi],
            "think_time": spec.think_time,
            "seed": spec.seed,
        },
        "offered": len(rows),
        "completed": len(done),
        "failed": sum(1 for r in rows if r["status"] == "failed"),
        "shed": sum(1 for r in rows if r["status"] == "shed"),
        "duration_s": wall,
        "ttft_first_turn": _percentiles(first),
        "ttft_follow_up": _percentiles(later),
        "follow_up_prefix_tokens": _percentiles(
            [float(r["prefix_len"]) for r in done if r["turn"] > 0]),
        "per_request": rows,
    }


def _build_scheduler(args):
    import jax
    import jax.numpy as jnp

    from cloud_tpu.serving.scheduler import Scheduler
    from cloud_tpu.serving.smoke import build_model

    model = build_model(num_layers=args.layers)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    pages_per_slot = model.max_seq_len // args.page_size
    num_pages = args.num_pages or None
    slots_min = getattr(args, "slots_min", None)
    slots_max = getattr(args, "slots_max", None)
    if num_pages is None and slots_min is None and slots_max is None:
        # Fixed geometry keeps the historic pool size; an elastic
        # ladder lets the Scheduler size the pool for its widest rung.
        num_pages = (args.slots + 4) * pages_per_slot + 1
    return Scheduler(model, params, slots=args.slots,
                     page_size=args.page_size,
                     num_pages=num_pages,
                     admission_window=args.slots,
                     strict_no_retrace=False,
                     kv_dtype=args.kv_dtype,
                     host_tier=args.host_tier,
                     slots_min=slots_min,
                     slots_max=slots_max,
                     admission_model=getattr(args, "admission_model",
                                             None))


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="open-arrival load generator for graftserve")
    parser.add_argument("--rate", type=float, action="append",
                        help="arrivals/sec; repeat for a load sweep "
                        "(default: one run at 8.0)")
    parser.add_argument("--requests", type=int, default=20)
    parser.add_argument("--process", default="poisson",
                        choices=("poisson", "bursty"))
    parser.add_argument("--burstiness", type=float, default=4.0)
    parser.add_argument("--shared-prefix-ratio", type=float,
                        default=0.5)
    parser.add_argument("--shared-prefix-len", type=int, default=16)
    parser.add_argument("--slo-ttft", type=float, default=None)
    parser.add_argument("--slo-tpot", type=float, default=None)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--page-size", type=int, default=16)
    parser.add_argument("--num-pages", type=int, default=0,
                        help="KV pool pages (0 = slots+4 sequences); "
                        "set small to force trie eviction between "
                        "conversation turns")
    parser.add_argument("--layers", type=int, default=6,
                        help="model depth (2 keeps CI fast)")
    parser.add_argument("--scenario", default="open",
                        choices=("open", "conversation", "diurnal"),
                        help="open-arrival singles, multi-turn "
                        "conversations (the host-tier workload), or a "
                        "sinusoidal-ramp offered rate (the autoscale "
                        "A/B workload)")
    parser.add_argument("--rate-lo", type=float, default=2.0,
                        help="diurnal trough arrivals/sec")
    parser.add_argument("--rate-hi", type=float, default=16.0,
                        help="diurnal crest arrivals/sec")
    parser.add_argument("--segments", type=int, default=6)
    parser.add_argument("--segment-seconds", type=float, default=2.0)
    parser.add_argument("--slots-min", type=int, default=None,
                        help="elastic ladder floor (enables graftflex "
                        "autoscaling; default: CLOUD_TPU_SERVE_"
                        "SLOTS_MIN)")
    parser.add_argument("--slots-max", type=int, default=None,
                        help="elastic ladder ceiling (default: "
                        "CLOUD_TPU_SERVE_SLOTS_MAX)")
    parser.add_argument("--admission-model", default=None,
                        help="fitted admission model JSON (default: "
                        "CLOUD_TPU_SERVE_ADMISSION_MODEL)")
    parser.add_argument("--conversations", type=int, default=4)
    parser.add_argument("--turns", type=int, default=3)
    parser.add_argument("--user-tokens", type=int, default=8)
    parser.add_argument("--think-time", type=float, default=0.05)
    parser.add_argument("--kv-dtype", default=None,
                        help="KV page dtype: '' (compute dtype) or "
                        "int8 (default: CLOUD_TPU_SERVE_KV_DTYPE)")
    parser.add_argument("--host-tier", default=None, type=int,
                        help="1 = demote finished turns to host RAM "
                        "(default: CLOUD_TPU_SERVE_HOST_TIER)")
    parser.add_argument("--out-dir", default="loadgen-out")
    args = parser.parse_args(argv)
    if args.host_tier is not None:
        args.host_tier = bool(args.host_tier)

    os.makedirs(args.out_dir, exist_ok=True)
    from cloud_tpu.serving import reqtrace
    if reqtrace.env_enabled() and reqtrace.get() is None:
        # Default the trace next to the report so one --out-dir is the
        # whole artifact (CLOUD_TPU_REQTRACE_DIR still wins).
        os.environ.setdefault("CLOUD_TPU_REQTRACE_DIR", args.out_dir)

    scheduler = _build_scheduler(args)
    scheduler.start()
    if args.scenario == "conversation":
        return _main_conversation(args, scheduler)
    if args.scenario == "diurnal":
        return _main_diurnal(args, scheduler)
    rates = args.rate or [8.0]
    specs = [LoadSpec(rate=rate, n_requests=args.requests,
                      process=args.process,
                      burstiness=args.burstiness,
                      shared_prefix_ratio=args.shared_prefix_ratio,
                      shared_prefix_len=args.shared_prefix_len,
                      seed=args.seed + i)
             for i, rate in enumerate(rates)]
    runs = []
    try:
        all_requests = []
        for spec in specs:
            all_requests.extend(build_requests(
                spec, scheduler.engine.model.vocab_size,
                scheduler.engine.max_seq_len))
        buckets = sorted({scheduler._bucket(r) for r in all_requests})
        print("[loadgen] warmup over buckets {}".format(buckets))
        scheduler.warmup(buckets,
                         sampling_configs=[(("temperature", 0.0),)])
        for spec in specs:
            print("[loadgen] {} x{} @ {:.3g} req/s".format(
                spec.process, spec.n_requests, spec.rate))
            run = run_load(scheduler, spec, slo_ttft=args.slo_ttft,
                           slo_tpot=args.slo_tpot)
            print("[loadgen]   offered {:.3g} rps, achieved {:.3g} "
                  "rps, goodput {:.3f}, ttft p95 {}".format(
                      run["offered_rps"], run["achieved_rps"],
                      run["goodput"], run["ttft"]["p95"]))
            runs.append(run)
        stats = scheduler.stats()
    finally:
        scheduler.close()
        tracer = reqtrace.get()
        if tracer is not None:
            tracer.flush()

    report = {
        "format": "cloud_tpu.loadgen_sweep.v1",
        "runs": runs,
        "scheduler_stats": {
            "queue_wait": stats["queue_wait"],
            "reserve_wait": stats["reserve_wait"],
            "ttft": stats["ttft"],
            "prefix_hit_rate": stats["prefix_hit_rate"],
        },
    }
    out_path = os.path.join(args.out_dir, "loadgen_report.json")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    print("[loadgen] wrote {}".format(out_path))
    return 0


def _main_conversation(args, scheduler):
    """Conversation-scenario driver: warm every pow2 bucket (turn
    prompts grow at runtime, so any width can appear), run the
    sessions, report the first-turn vs follow-up TTFT split plus the
    scheduler's demote/promote census."""
    from cloud_tpu.serving import reqtrace
    spec = ConversationSpec(
        n_sessions=args.conversations, n_turns=args.turns,
        user_tokens=args.user_tokens, think_time=args.think_time,
        seed=args.seed)
    try:
        print("[loadgen] warmup (all pow2 buckets)")
        scheduler.warmup([scheduler.engine.max_seq_len],
                         sampling_configs=[(("temperature", 0.0),)])
        print("[loadgen] conversations x{} turns x{}".format(
            spec.n_sessions, spec.n_turns))
        run = run_conversations(scheduler, spec)
        stats = scheduler.stats()
        # Leak detector: every session thread has joined, so after the
        # tick thread quiesces the pool must hold nothing beyond the
        # trie's own references — the CI offload job gates on this.
        time.sleep(0.3)
        scheduler.assert_drained(clear_prefix=True)
        leaked = scheduler.pool.leak_report()
    finally:
        scheduler.close()
        tracer = reqtrace.get()
        if tracer is not None:
            tracer.flush()
    print("[loadgen]   completed {}/{}: ttft p50 first {} follow-up "
          "{}".format(run["completed"], run["offered"],
                      run["ttft_first_turn"]["p50"],
                      run["ttft_follow_up"]["p50"]))
    report = {
        "format": "cloud_tpu.loadgen_sweep.v1",
        "runs": [run],
        "scheduler_stats": {
            "ttft": stats["ttft"],
            "prefix_hit_rate": stats["prefix_hit_rate"],
            "kv": stats["kv"],
            "leaked_pages": leaked,
        },
    }
    out_path = os.path.join(args.out_dir, "loadgen_report.json")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    print("[loadgen] wrote {}".format(out_path))
    return 0


def _main_diurnal(args, scheduler):
    """Diurnal-scenario driver: warm every bucket the per-segment
    request populations will hit (plus the resize ladder, which
    warmup() walks on its own when one is configured), run the ramp,
    and report the goodput-vs-offered curve next to the scheduler's
    geometry census."""
    from cloud_tpu.serving import reqtrace
    spec = DiurnalSpec(
        rate_lo=args.rate_lo, rate_hi=args.rate_hi,
        segments=args.segments, segment_s=args.segment_seconds,
        process=args.process, burstiness=args.burstiness,
        shared_prefix_ratio=args.shared_prefix_ratio,
        shared_prefix_len=args.shared_prefix_len, seed=args.seed)
    try:
        vocab = scheduler.engine.model.vocab_size
        max_seq_len = scheduler.engine.max_seq_len
        all_requests = []
        for k, rate in enumerate(spec.segment_rates()):
            seg_spec = LoadSpec(
                rate=rate,
                n_requests=max(1, int(round(rate * spec.segment_s))),
                process=spec.process, burstiness=spec.burstiness,
                shared_prefix_ratio=spec.shared_prefix_ratio,
                shared_prefix_len=spec.shared_prefix_len,
                seed=spec.seed + 101 * k + 1)
            all_requests.extend(build_requests(seg_spec, vocab,
                                               max_seq_len))
        buckets = sorted({scheduler._bucket(r) for r in all_requests})
        print("[loadgen] warmup over buckets {} ladder {}".format(
            buckets, list(scheduler.engine.ladder)))
        scheduler.warmup(buckets,
                         sampling_configs=[(("temperature", 0.0),)])
        print("[loadgen] diurnal {} segments x {:.3g}s, {:.3g} -> "
              "{:.3g} req/s".format(spec.segments, spec.segment_s,
                                    spec.rate_lo, spec.rate_hi))
        run = run_diurnal(scheduler, spec, slo_ttft=args.slo_ttft,
                          slo_tpot=args.slo_tpot)
        for seg in run["offered_curve"]:
            print("[loadgen]   seg {} @ {:.3g} rps: goodput {:.3f}, "
                  "ttft p99 {}".format(seg["segment"],
                                       seg["offered_rate"],
                                       seg["goodput"],
                                       seg["ttft"]["p99"]))
        stats = scheduler.stats()
    finally:
        scheduler.close()
        tracer = reqtrace.get()
        if tracer is not None:
            tracer.flush()
    geometry = stats.get("geometry", {})
    print("[loadgen]   goodput {:.3f}, worst seg ttft p99 {}, resizes "
          "{}".format(run["goodput"], run["worst_ttft_p99"],
                      geometry.get("resizes")))
    report = {
        "format": "cloud_tpu.loadgen_sweep.v1",
        "runs": [run],
        "scheduler_stats": {
            "queue_wait": stats["queue_wait"],
            "ttft": stats["ttft"],
            "prefix_hit_rate": stats["prefix_hit_rate"],
            "geometry": geometry,
            "admission_predictor": stats.get("admission_predictor"),
        },
    }
    out_path = os.path.join(args.out_dir, "loadgen_report.json")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    print("[loadgen] wrote {}".format(out_path))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
