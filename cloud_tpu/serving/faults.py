"""graftstorm fault taxonomy: typed serving faults, parallel to
training/resilience.py's TrainingFault.

graftguard answers training faults by KIND (rescue checkpoint for a
Preemption, quarantine+rollback for corruption, …); the serving stack
needs the same discipline per slot. Every chaos injection or runtime
failure that hits an in-flight request is surfaced as one of these
types so the Scheduler can answer mechanically:

  SlotHang / SlotEvicted / PoolSqueezed -> drain the victim slot via
      the fixed-shape evict scatter, free its pages exactly once, and
      REQUEUE the request: re-prefill from retained prompt + tokens
      generated so far with the original per-slot rng schedule
      re-based, completing bit-identical to an uninterrupted run.
  PrefillFailed -> transient; release any reserved pages and retry the
      prefill (the request never entered a slot, nothing to drain).
  ServeShed -> terminal by POLICY, not failure: SLO-aware admission
      predicted the request cannot meet its TTFT target and refused
      it. Carries the prediction so callers/loadgen can report shed
      separately from genuine failures.

`fault_kind(exc)` mirrors resilience.fault_kind: a stable string for
telemetry labels and reqtrace payloads.
"""

__all__ = ["ServeFault", "SlotHang", "SlotEvicted", "PrefillFailed",
           "PoolSqueezed", "ServeShed", "HostTierCorrupt",
           "fault_kind"]


class ServeFault(RuntimeError):
    """Base class for typed serving faults (taxonomy root)."""

    fault_kind = "serve_fault"


class SlotHang(ServeFault):
    """A decode slot stopped making progress (wedged dispatch, chaos
    `slot_hang@tick`); the slot drains and its request requeues."""

    fault_kind = "slot_hang"


class SlotEvicted(ServeFault):
    """A slot's pages were reclaimed out from under it (preempted
    hardware, chaos `slot_evict@tick:slot`); the request requeues."""

    fault_kind = "slot_evict"


class PrefillFailed(ServeFault):
    """A prefill dispatch failed transiently (chaos
    `prefill_fail@tick`); reserved pages are released and the prefill
    retries — the request stays queued, never lost."""

    fault_kind = "prefill_fail"


class PoolSqueezed(ServeFault):
    """Free KV pages were confiscated (chaos `pool_squeeze@tick:pages`
    — a neighbor claiming HBM); admission backpressure absorbs it, and
    any slot drained to cover the squeeze requeues."""

    fault_kind = "pool_squeeze"


class HostTierCorrupt(ServeFault):
    """A host-tier page entry failed its tree_digest check at promote
    time (graftpack): the entry is dropped and admission falls back to
    re-prefilling the history — corrupt pages are never mapped into a
    slot. Counted, never fatal to the request."""

    fault_kind = "host_tier_corrupt"


class ServeShed(ServeFault):
    """Admission control refused the request: predicted TTFT exceeds
    the SLO target. Not a malfunction — the policy outcome callers
    asked for with CLOUD_TPU_SERVE_SLO_TTFT + CLOUD_TPU_SERVE_SHED."""

    fault_kind = "shed"

    def __init__(self, message, reason="predicted", predicted_ttft=None,
                 slo_ttft=None):
        super().__init__(message)
        self.reason = reason
        self.predicted_ttft = predicted_ttft
        self.slo_ttft = slo_ttft


def fault_kind(exc):
    """Stable taxonomy label for an exception: the ServeFault kind, or
    "unknown" for anything outside the taxonomy."""
    if isinstance(exc, ServeFault):
        return type(exc).fault_kind
    return "unknown"
