"""graftserve decode engine: slot-indexed continuous decode tick.

One persistent jitted executable (`tick`) advances every active slot one
token per call over the paged KV pool. Requests enter mid-flight — a
dense prefill (compiled per pow2 bucket, off the tick's critical path)
is scattered into a free slot's pages by the `insert` executable — and
leave mid-flight: the `evict` executable zeros the finished slots'
page-table/validity rows without stopping the tick. All three are
`runtime.instrumented_jit` sites with fixed shapes, so after warm-up the
compile counters are a retrace sentinel the engine can enforce.

Bit-identical contract: a request decoded through the engine produces
exactly the tokens `models.transformer.generate()` would produce for it
solo (same rng, same sampling config). The engine reuses generate()'s
OWN prefill executable and rng schedule, and the paged tick reproduces
the dense decode math per slot — per-slot sampling parameters are
dynamic arrays whose disabled values (top_k = vocab, top_p = 1.0) are
exact no-ops, so one tick executable serves every sampling config. See
tests/unit/test_serving.py for the enforced oracle.
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from cloud_tpu.parallel import runtime


class RetraceError(RuntimeError):
    """The warm engine traced or compiled something new — a static-shape
    leak in the serving path (the retrace sentinel)."""


@dataclasses.dataclass
class PrefillResult:
    """A prefilled request waiting for slot insertion."""
    first_token: int        # sampled from the prompt's last position
    pcache: object          # dense [1, L] decode cache (device)
    step_keys: np.ndarray   # [K, 2] uint32, generate()'s split schedule
    bucket: int             # pow2 prefill bucket (pages were sized off it)
    n_steps: int            # max_new_tokens for this request


def _plain(tree):
    """Nested-Mapping pytree -> plain dicts (flax may hand back
    FrozenDicts; keep one structure so donation pairs buffers)."""
    try:
        items = tree.items()
    except AttributeError:
        return tree
    return {k: _plain(v) for k, v in items}


def _map_attention(cache, fn, *rest):
    """Applies `fn` to every paged-attention subtree (detected by its
    `key_pages` variable), walking `rest` trees in parallel."""
    if isinstance(cache, dict):
        if "key_pages" in cache:
            return fn(cache, *rest)
        return {k: _map_attention(cache[k], fn,
                                  *[r[k] if isinstance(r, dict) else r
                                    for r in rest])
                for k in cache}
    return cache


def _sample_one(logits, key, temperature, top_k, top_p):
    """One slot's sampler: `generate()`'s sample() with the sampling
    config as runtime values. Disabled values are exact identities —
    top_k = vocab keeps every logit, top_p = 1.0 selects the unwarped
    branch, temperature = 0 selects greedy — so the warped results are
    bitwise those of `decoding.warp_logits` with the static config.
    """
    lf = logits.astype(jnp.float32)
    greedy = jnp.argmax(lf, axis=-1).astype(jnp.int32)
    # kth-largest VALUE equals lax.top_k(...)[0][-1] for any tie
    # pattern, so the `< kth` mask matches the static warper's.
    kth = jnp.take(jnp.flip(jnp.sort(lf)), top_k - 1)
    lk = jnp.where(lf < kth, -1e30, lf)
    scaled = lk / jnp.where(temperature > 0.0, temperature, 1.0)
    # Nucleus membership in descending sorted order, scattered back
    # through the inverse permutation — warp_logits' exact recipe
    # (including its scatter-built inverse).
    sort_idx = jnp.flip(jnp.argsort(scaled))
    sorted_scaled = scaled[sort_idx]
    probs = jax.nn.softmax(sorted_scaled)
    cum = jnp.cumsum(probs)
    inv = jnp.zeros_like(sort_idx).at[sort_idx].set(
        jnp.arange(sort_idx.shape[0]))
    keep = (cum - probs < top_p)[inv]
    warped = jnp.where(top_p < 1.0,
                       jnp.where(keep, scaled, -1e30), scaled)
    sampled = jax.random.categorical(key, warped).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


def _sample_slots(logits, keys, temperature, top_k, top_p):
    """All-slot sampler with a greedy fast path: the sorts behind
    top-k/top-p cost more than the whole model apply at smoke scale
    (XLA CPU sort), so a tick whose ACTIVE traffic is all greedy picks
    the argmax branch at runtime — one executable either way, and the
    sampled branch is `_sample_one` verbatim."""
    greedy = jnp.argmax(logits.astype(jnp.float32),
                        axis=-1).astype(jnp.int32)
    return jax.lax.cond(
        jnp.any(temperature > 0.0),
        lambda: jax.vmap(_sample_one)(logits, keys, temperature,
                                      top_k, top_p),
        lambda: greedy)


class DecodeEngine:
    """Continuous-batching decode over `slots` slots of a paged pool.

    Single-owner device state: exactly one thread may call
    `insert`/`tick`/`evict` (the scheduler's tick thread); `prefill`
    is safe to call concurrently from an admission thread.
    """

    def __init__(self, model, params, slots, page_size, num_pages,
                 max_new_cap=None):
        from cloud_tpu.models.transformer import TransformerLM

        if not isinstance(model, TransformerLM):
            raise NotImplementedError(
                "graftserve v1 serves TransformerLM (dense causal "
                "attention); got {}.".format(type(model).__name__))
        if model.max_seq_len % page_size:
            raise ValueError(
                "max_seq_len ({}) must be a multiple of page_size "
                "({}).".format(model.max_seq_len, page_size))
        self.model = model
        self.slots = int(slots)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.pages_per_slot = model.max_seq_len // page_size
        self.max_seq_len = model.max_seq_len
        self.max_new_cap = int(max_new_cap or model.max_seq_len)
        if self.max_new_cap < 2:
            raise ValueError("max_new_cap must be >= 2.")
        self._params = params
        # The SAME decode clone generate() derives, so the engine's
        # prefill executables and cache-pool entries are shared with
        # solo generate() calls in the process.
        self._dense = model.clone(decode=True, dropout_rate=0.0)
        self._paged = model.clone(decode=True, dropout_rate=0.0,
                                  kv_page_size=page_size,
                                  kv_num_pages=num_pages)

        from cloud_tpu.models.decoding import (best_effort_donation,
                                               empty_cache)
        self.cache = _plain(empty_cache(self._paged, self.slots))
        key_width = self.max_new_cap - 1
        self.ctl = {
            "active": jnp.zeros((slots,), jnp.bool_),
            "done": jnp.zeros((slots,), jnp.bool_),
            "cur_tok": jnp.zeros((slots,), jnp.int32),
            "steps_done": jnp.zeros((slots,), jnp.int32),
            "max_steps": jnp.zeros((slots,), jnp.int32),
            "temperature": jnp.zeros((slots,), jnp.float32),
            "top_k": jnp.ones((slots,), jnp.int32),
            "top_p": jnp.ones((slots,), jnp.float32),
            "eos": jnp.zeros((slots,), jnp.int32),
            "has_eos": jnp.zeros((slots,), jnp.bool_),
            "step_keys": jnp.zeros((slots, key_width, 2), jnp.uint32),
        }
        self._tick = best_effort_donation(functools.partial(
            runtime.instrumented_jit, donate_argnums=(1, 2))(
                self._tick_impl))
        self._insert = best_effort_donation(functools.partial(
            runtime.instrumented_jit, donate_argnums=(0, 1))(
                self._insert_impl))
        self._evict = best_effort_donation(functools.partial(
            runtime.instrumented_jit, donate_argnums=(0, 1))(
                self._evict_impl))
        self._warm_stats = None

    # -- prefill (admission thread) -----------------------------------

    def prefill(self, prompt, max_new_tokens, rng, sampling):
        """Dense prefill for one request, exactly `generate()`'s path:
        same bucket, same left-pad + mask, same executable (shared
        `_decode_fns` entry), same rng split schedule. `sampling` is a
        normalized dict: temperature (float), top_k (int|None), top_p
        (float|None), eos_token (int|None). Returns a `PrefillResult`;
        blocks until the first token is on host (the TTFT point)."""
        from cloud_tpu.models.decoding import (acquire_cache,
                                               bucket_length)
        from cloud_tpu.models.transformer import _decode_fns

        prompt = np.asarray(prompt, np.int32).reshape(1, -1)
        prompt_len = prompt.shape[1]
        prefill_fn, _ = _decode_fns(
            self._dense, float(sampling["temperature"]),
            sampling["top_k"], sampling["top_p"], sampling["eos_token"])
        key, prefill_rng = jax.random.split(rng)
        mask_arg = None
        prefill_tokens = jnp.asarray(prompt)
        bucket = bucket_length(prompt_len,
                               self.max_seq_len - max_new_tokens)
        if bucket > prompt_len:
            pad = bucket - prompt_len
            prefill_tokens = jnp.pad(prefill_tokens, ((0, 0), (pad, 0)))
            mask_arg = jnp.pad(jnp.ones((1, prompt_len), bool),
                               ((0, 0), (pad, 0)))
        cache = acquire_cache(self._dense, 1)
        pcache, first = prefill_fn(self._params, cache, prefill_tokens,
                                   prefill_rng, mask_arg)
        step_keys = np.zeros((self.max_new_cap - 1, 2), np.uint32)
        if max_new_tokens > 1:
            step_keys[:max_new_tokens - 1] = np.asarray(
                jax.random.split(key, max_new_tokens - 1))
        first_host = int(runtime.device_fetch(first)[0])
        return PrefillResult(first_token=first_host, pcache=pcache,
                             step_keys=step_keys, bucket=bucket,
                             n_steps=int(max_new_tokens))

    def release_prefill(self, result):
        """Parks a consumed (or abandoned) prefill's dense cache back
        in the decode-cache reuse pool."""
        from cloud_tpu.models.decoding import release_cache
        release_cache(self._dense, 1, result.pcache)
        result.pcache = None

    # -- slot ops (tick thread) ---------------------------------------

    def insert(self, slot, result, page_vec, sampling):
        """Writes a prefilled request into free slot `slot`: scatters
        the dense prefill cache into the reserved pages, installs the
        page-table/validity/step rows, and arms the slot's control row
        (sampling params, rng schedule, eos latch). One fixed-shape
        executable for every bucket — the prefill cache is always
        full-length dense."""
        vocab = self.model.vocab_size
        top_k = sampling["top_k"]
        top_p = sampling["top_p"]
        eos = sampling["eos_token"]
        self.cache, self.ctl = self._insert(
            self.cache, self.ctl, _plain(result.pcache),
            np.int32(slot), jnp.asarray(page_vec, jnp.int32),
            jnp.asarray(result.step_keys),
            np.int32(result.n_steps), np.int32(result.first_token),
            np.float32(sampling["temperature"]),
            np.int32(vocab if top_k is None else top_k),
            np.float32(1.0 if top_p is None else top_p),
            np.int32(0 if eos is None else eos),
            bool(eos is not None))
        self.release_prefill(result)

    def tick(self):
        """Advances every active slot one token. Returns the device
        out-array `[2, S]` (row 0: sampled token, row 1: finished flag)
        — the scheduler fetches it with `runtime.device_fetch`."""
        self.cache, self.ctl, out = self._tick(
            self._params, self.cache, self.ctl)
        return out

    def evict(self, evict_mask):
        """Frees every slot where `evict_mask` is True: page-table and
        validity rows go back to scratch/zero, the control row disarms.
        The physical page ids go back to the host pool separately
        (scheduler bookkeeping)."""
        self.cache, self.ctl = self._evict(
            self.cache, self.ctl, jnp.asarray(evict_mask, bool))

    # -- retrace sentinel ---------------------------------------------

    def mark_warm(self):
        """Snapshots the compile counters; `check_no_retrace()` raises
        on any growth after this point."""
        self._warm_stats = runtime.compile_stats()

    def check_no_retrace(self):
        if self._warm_stats is None:
            return
        now = runtime.compile_stats()
        grew = {k: now[k] - self._warm_stats[k]
                for k in ("n_traces", "n_compiles")
                if now[k] > self._warm_stats[k]}
        if grew:
            raise RetraceError(
                "serving path traced/compiled after warm-up: {} "
                "(static-shape leak).".format(grew))

    # -- jitted bodies ------------------------------------------------

    def _tick_impl(self, params, cache, ctl):
        active = ctl["active"]
        logits, vars_ = self._paged.apply(
            {"params": params, "cache": cache},
            ctl["cur_tok"][:, None], active[:, None], mutable=["cache"])
        logits = logits[:, 0]  # [S, V]
        # Slot s's step i consumes generate()'s step_rngs[i]; after
        # insertion steps_done is 1 (the prefill token), so the first
        # tick reads key row 0.
        key_idx = jnp.clip(ctl["steps_done"] - 1, 0,
                           ctl["step_keys"].shape[1] - 1)
        keys = jnp.take_along_axis(
            ctl["step_keys"], key_idx[:, None, None], 1)[:, 0]
        # Inactive slots keep their stale sampling rows; zeroing the
        # temperature they feed the sampler keeps the greedy fast path
        # available whenever the LIVE traffic is all-greedy.
        live_temp = jnp.where(active, ctl["temperature"], 0.0)
        nxt = _sample_slots(logits, keys, live_temp, ctl["top_k"],
                            ctl["top_p"])
        latched = ctl["has_eos"] & ctl["done"]
        nxt = jnp.where(latched, ctl["eos"], nxt)
        done = ctl["done"] | (ctl["has_eos"] & (nxt == ctl["eos"]))
        steps = ctl["steps_done"] + active.astype(jnp.int32)
        finished = active & (done | (steps >= ctl["max_steps"]))
        out_ctl = dict(ctl)
        out_ctl["cur_tok"] = jnp.where(active, nxt, ctl["cur_tok"])
        out_ctl["done"] = jnp.where(active, done, ctl["done"])
        out_ctl["steps_done"] = steps
        out = jnp.stack([jnp.where(active, nxt, -1),
                         finished.astype(jnp.int32)])
        return _plain(vars_["cache"]), out_ctl, out

    def _insert_impl(self, cache, ctl, pcache, slot, page_vec,
                     step_keys_row, max_steps, first_tok, temperature,
                     top_k, top_p, eos, has_eos):
        ppn, page = self.pages_per_slot, self.page_size

        def scatter(att, patt):
            out = dict(att)
            # Reserved ids are unique and nonzero, so real chunks land
            # exactly; the duplicate scratch entries all carry the
            # prefill cache's zero tail (never read either way).
            chunks_k = patt["cached_key"][0].reshape(
                ppn, page, *patt["cached_key"].shape[2:])
            chunks_v = patt["cached_value"][0].reshape(
                ppn, page, *patt["cached_value"].shape[2:])
            out["key_pages"] = att["key_pages"].at[page_vec].set(chunks_k)
            out["value_pages"] = att["value_pages"].at[page_vec].set(
                chunks_v)
            out["page_table"] = att["page_table"].at[slot].set(page_vec)
            out["slot_steps"] = att["slot_steps"].at[slot].set(
                patt["cache_index"])
            out["slot_valid"] = att["slot_valid"].at[slot].set(
                patt["slot_valid"][0])
            return out

        new_cache = _map_attention(cache, scatter, pcache)
        new_cache["pos_count"] = cache["pos_count"].at[slot].set(
            pcache["pos_count"][0])
        out_ctl = dict(ctl)
        out_ctl["active"] = ctl["active"].at[slot].set(True)
        out_ctl["done"] = ctl["done"].at[slot].set(
            has_eos & (first_tok == eos))
        out_ctl["cur_tok"] = ctl["cur_tok"].at[slot].set(first_tok)
        out_ctl["steps_done"] = ctl["steps_done"].at[slot].set(1)
        out_ctl["max_steps"] = ctl["max_steps"].at[slot].set(max_steps)
        out_ctl["temperature"] = ctl["temperature"].at[slot].set(
            temperature)
        out_ctl["top_k"] = ctl["top_k"].at[slot].set(top_k)
        out_ctl["top_p"] = ctl["top_p"].at[slot].set(top_p)
        out_ctl["eos"] = ctl["eos"].at[slot].set(eos)
        out_ctl["has_eos"] = ctl["has_eos"].at[slot].set(has_eos)
        out_ctl["step_keys"] = ctl["step_keys"].at[slot].set(
            step_keys_row)
        return new_cache, out_ctl

    def _evict_impl(self, cache, ctl, evict_mask):
        keep = ~evict_mask

        def clear(att):
            out = dict(att)
            out["page_table"] = jnp.where(keep[:, None],
                                          att["page_table"], 0)
            out["slot_steps"] = jnp.where(keep, att["slot_steps"], 0)
            out["slot_valid"] = att["slot_valid"] & keep[:, None]
            return out

        new_cache = _map_attention(cache, clear)
        new_cache["pos_count"] = jnp.where(keep, cache["pos_count"], 0)
        out_ctl = dict(ctl)
        out_ctl["active"] = ctl["active"] & keep
        out_ctl["done"] = ctl["done"] & keep
        out_ctl["steps_done"] = jnp.where(keep, ctl["steps_done"], 0)
        out_ctl["cur_tok"] = jnp.where(keep, ctl["cur_tok"], 0)
        out_ctl["max_steps"] = jnp.where(keep, ctl["max_steps"], 0)
        return new_cache, out_ctl


__all__ = ["DecodeEngine", "PrefillResult", "RetraceError"]
