"""graftserve decode engine: slot-indexed continuous decode tick.

One persistent jitted executable (`tick`) advances every active slot
over the paged KV pool — one token per call in the plain engine, up to
`spec_k + 1` tokens per call when a draft model rides along (per-slot
draft/verify speculation). Requests enter mid-flight — a dense prefill
(compiled per pow2 suffix bucket, off the tick's critical path) is
scattered into a free slot's pages by the `insert` executable — and
leave mid-flight: the `evict` executable zeros the finished slots'
page-table/validity rows without stopping the tick. All executables are
`runtime.instrumented_jit` sites with fixed shapes, so after warm-up
the compile counters are a retrace sentinel the engine can enforce.

Canonical right-pad prefill (the prefix-sharing layout): prompt token i
is written at cache slot i, the pad tail is right of the real tokens
and invalid. Page content is therefore position-independent — the page
holding positions [16, 32) of a prompt is bitwise the page any OTHER
request with the same prefix would produce — which is what lets the
radix prefix cache (serving/prefixcache.py) map one physical page into
many slots' page tables. Pad slots carry exact-zero attention weight
(-1e30 mask -> softmax 0.0) and positions count only real tokens, so
right-pad output is bitwise the left-pad output generate() computes.

Prefix reuse: `prefill(prefix_len=, gather_vec=)` seeds the dense
prefill cache from already-resident pool pages (one gather + zeroed
invalid tail) and runs the model over the SUFFIX only — TTFT drops
from O(prompt) to O(suffix). At insert, `scatter_vec` routes chunk i
either to its fresh page (owned/divergent content — the copy-on-write
copy happens here, device-side, fixed shape) or to the scratch page
(shared content already resident; the slot's page table still points
at the shared page).

Bit-identical contract: a request decoded through the engine produces
exactly the tokens `models.transformer.generate()` would produce for it
solo (same rng, same sampling config) — with or without prefix sharing
or speculation. Greedy slots accept draft tokens only where they equal
the target argmax (`speculative.greedy_accept`); sampled slots ride the
same executable committing one token from the verify window's first
position, whose logits are bitwise the single-token tick's. See
tests/unit/test_serving.py and tests/unit/test_prefix_cache.py for the
enforced oracles.
"""

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from cloud_tpu.monitoring import spans
from cloud_tpu.parallel import runtime


class RetraceError(RuntimeError):
    """The warm engine traced or compiled something new — a static-shape
    leak in the serving path (the retrace sentinel)."""


@dataclasses.dataclass
class PrefillResult:
    """A prefilled request waiting for slot insertion."""
    first_token: int        # sampled from the prompt's last position
    pcache: object          # dense [1, L] decode cache (device)
    dpcache: object         # draft-model dense cache (None unless spec)
    step_keys: np.ndarray   # [K, 2] uint32, generate()'s split schedule
    bucket: int             # pow2 SUFFIX bucket the prefill compiled at
    n_steps: int            # max_new_tokens for this request
    prompt_len: int         # full prompt length (prefix + suffix)


def _plain(tree):
    """Nested-Mapping pytree -> plain dicts (flax may hand back
    FrozenDicts; keep one structure so donation pairs buffers)."""
    try:
        items = tree.items()
    except AttributeError:
        return tree
    return {k: _plain(v) for k, v in items}


def _map_attention(cache, fn, *rest):
    """Applies `fn` to every paged-attention subtree (detected by its
    `key_pages` variable), walking `rest` trees in parallel."""
    if isinstance(cache, dict):
        if "key_pages" in cache:
            return fn(cache, *rest)
        return {k: _map_attention(cache[k], fn,
                                  *[r[k] if isinstance(r, dict) else r
                                    for r in rest])
                for k in cache}
    return cache


_GATHER_READS = ("key_pages", "value_pages", "key_scales",
                 "value_scales")


def _pool_pages_view(cache):
    """Geometry-free view of a pool cache for the prefix gather. The
    gather reads only the page arrays (pool-indexed, fixed shape), but
    per-slot state (page_table [slots, ppn], slot_steps [slots],
    slot_valid [slots, L], pos_count [slots]) rides along in the
    pytree and would bind the executable's signature to one slot
    count — a prefix hit after an elastic resize would then retrace.
    Whitelisting the page arrays here, outside the jit boundary (keys
    kept, unread leaves None'd), keeps one executable across every
    geometry rung."""
    view = _map_attention(
        cache, lambda att: {k: (v if k in _GATHER_READS else None)
                            for k, v in att.items()})
    view["pos_count"] = None
    return view


def _sample_one(logits, key, temperature, top_k, top_p):
    """One slot's sampler: `generate()`'s sample() with the sampling
    config as runtime values. Disabled values are exact identities —
    top_k = vocab keeps every logit, top_p = 1.0 selects the unwarped
    branch, temperature = 0 selects greedy — so the warped results are
    bitwise those of `decoding.warp_logits` with the static config.
    """
    lf = logits.astype(jnp.float32)
    greedy = jnp.argmax(lf, axis=-1).astype(jnp.int32)
    # kth-largest VALUE equals lax.top_k(...)[0][-1] for any tie
    # pattern, so the `< kth` mask matches the static warper's.
    kth = jnp.take(jnp.flip(jnp.sort(lf)), top_k - 1)
    lk = jnp.where(lf < kth, -1e30, lf)
    scaled = lk / jnp.where(temperature > 0.0, temperature, 1.0)
    # Nucleus membership in descending sorted order, scattered back
    # through the inverse permutation — warp_logits' exact recipe
    # (including its scatter-built inverse).
    sort_idx = jnp.flip(jnp.argsort(scaled))
    sorted_scaled = scaled[sort_idx]
    probs = jax.nn.softmax(sorted_scaled)
    cum = jnp.cumsum(probs)
    inv = jnp.zeros_like(sort_idx).at[sort_idx].set(
        jnp.arange(sort_idx.shape[0]))
    keep = (cum - probs < top_p)[inv]
    warped = jnp.where(top_p < 1.0,
                       jnp.where(keep, scaled, -1e30), scaled)
    sampled = jax.random.categorical(key, warped).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


def _sample_slots(logits, keys, temperature, top_k, top_p):
    """All-slot sampler with a greedy fast path: the sorts behind
    top-k/top-p cost more than the whole model apply at smoke scale
    (XLA CPU sort), so a tick whose ACTIVE traffic is all greedy picks
    the argmax branch at runtime — one executable either way, and the
    sampled branch is `_sample_one` verbatim."""
    greedy = jnp.argmax(logits.astype(jnp.float32),
                        axis=-1).astype(jnp.int32)
    return jax.lax.cond(
        jnp.any(temperature > 0.0),
        lambda: jax.vmap(_sample_one)(logits, keys, temperature,
                                      top_k, top_p),
        lambda: greedy)


@functools.lru_cache(maxsize=64)
def _serve_prefill_fns(decoder, temperature, top_k, top_p):
    """Jitted canonical (right-pad) prefill for one decoder/sampling
    config: run the suffix window, sample the last REAL position's row.
    `last_idx` is dynamic, so every suffix length in a bucket shares
    the executable — including prefix-HIT suffixes starting mid-cache
    (the gathered cache's write pointer supplies the start). The row is
    kept [1, V] so the categorical draw matches `generate()` bitwise
    (same gumbel shape)."""

    @functools.partial(runtime.instrumented_jit, donate_argnums=1)
    def prefill(params, cache, tokens, rng, mask, last_idx):
        logits, vars_ = decoder.apply({"params": params, "cache": cache},
                                      tokens, mask, mutable=["cache"])
        row = jax.lax.dynamic_slice_in_dim(
            logits, last_idx, 1, axis=1)[:, 0].astype(jnp.float32)
        if not temperature:
            tok = jnp.argmax(row, axis=-1).astype(jnp.int32)
        else:
            from cloud_tpu.models.decoding import warp_logits
            warped = warp_logits(row, temperature, top_k, top_p)
            tok = jax.random.categorical(rng, warped,
                                         axis=-1).astype(jnp.int32)
        return vars_["cache"], tok

    from cloud_tpu.models.decoding import best_effort_donation
    return best_effort_donation(prefill)


@functools.lru_cache(maxsize=64)
def _cache_prefill_fn(decoder):
    """Jitted cache-only prefill: run a window, keep the cache, sample
    nothing. Two callers share it (per decoder, per window shape):
    draft-model prefills (the draft never emits tokens directly — it
    proposes inside the tick) and the INTERMEDIATE chunks of a chunked
    prefill, which only advance the cache — the tail chunk samples."""

    @functools.partial(runtime.instrumented_jit, donate_argnums=1)
    def prefill(params, cache, tokens, mask):
        _, vars_ = decoder.apply({"params": params, "cache": cache},
                                 tokens, mask, mutable=["cache"])
        return vars_["cache"]

    from cloud_tpu.models.decoding import best_effort_donation
    return best_effort_donation(prefill)


def chunk_plan(n_suffix, chunk_size, max_seq_len):
    """Chunk layout for an `n_suffix`-token prefill at fixed chunk
    width `chunk_size`: `(n_full, tail, tail_bucket)` — `n_full` full
    chunks of `chunk_size` real tokens, then one tail chunk of `tail`
    in [1, chunk_size] real tokens run at the pow2 `tail_bucket` width
    (the SAME executable family as a whole prefill of a short suffix,
    so single-chunk prefills degenerate to exactly today's path). With
    `chunk_size` a power of two the written extent
    `n_full * chunk_size + tail_bucket` never exceeds
    `bucket_length(n_suffix)`, so the whole-prefill in-cache check
    also bounds the chunked writes."""
    from cloud_tpu.models.decoding import bucket_length
    n_full = (n_suffix - 1) // chunk_size
    tail = n_suffix - n_full * chunk_size
    return n_full, tail, bucket_length(tail, max_seq_len)


class ChunkedPrefill:
    """An in-flight chunked prefill: one request's suffix split into
    fixed-width windows that the scheduler interleaves with decode
    ticks (`step()` runs ONE chunk; the final chunk returns the
    `PrefillResult` a whole prefill would have).

    Bit-identity: the dense decode attention always computes over the
    full [1, L] cache with per-position validity masks, and positions
    come from the running real-token count — so a window written in
    chunks holds bitwise the values the whole window writes, and the
    tail chunk's last-real-position logits (where the first token is
    sampled) are bitwise the whole prefill's. The rng schedule is
    untouched: only the tail chunk draws, with the same split the
    whole prefill uses.

    Construction is host-side only (the chunk PLAN); the first
    `step()` acquires the dense cache(s) and runs the optional prefix
    gather. Every device dispatch therefore happens on the stepping
    thread — the scheduler steps chunks on the tick thread, whose
    ticks donate the pool cache the gather reads."""

    def __init__(self, engine, prompt, max_new_tokens, rng, sampling,
                 chunk_size, prefix_len=0, gather_vec=None,
                 key_override=None):
        from cloud_tpu.models.decoding import bucket_length

        prompt = np.asarray(prompt, np.int32).reshape(-1)
        prompt_len = int(prompt.shape[0])
        prefix_len = int(prefix_len)
        if not 0 <= prefix_len < prompt_len:
            raise ValueError(
                "prefix_len must be in [0, prompt_len); got {} for a "
                "{}-token prompt.".format(prefix_len, prompt_len))
        n_suffix = prompt_len - prefix_len
        if prefix_len + bucket_length(
                n_suffix, engine.max_seq_len) > engine.max_seq_len:
            raise ValueError(
                "prefix ({}) + suffix bucket exceeds max_seq_len {}; "
                "the scheduler trims the match to keep the padded "
                "suffix in-cache.".format(prefix_len,
                                          engine.max_seq_len))
        self.engine = engine
        self.chunk_size = int(chunk_size)
        self.prompt_len = prompt_len
        self.prefix_len = prefix_len
        self.max_new_tokens = int(max_new_tokens)
        self._suffix = prompt[prefix_len:]
        self._sampling = dict(sampling)
        self._gather_vec = gather_vec
        n_full, tail, tail_bucket = chunk_plan(
            n_suffix, self.chunk_size, engine.max_seq_len)
        self.n_chunks = n_full + 1
        self.chunks_done = 0
        self._tail = tail
        self._tail_bucket = tail_bucket
        if key_override is None:
            self._key, self._prefill_rng = jax.random.split(rng)
            self._override_rest = None
        else:
            self._prefill_rng = jnp.asarray(key_override[0], jnp.uint32)
            self._key = None
            self._override_rest = key_override[1]
        self._cache = None
        self._dcache = None
        self._closed = False

    def chunk_tokens(self, i):
        """Real tokens chunk `i` carries (chunk_size, or the tail)."""
        return self.chunk_size if i < self.n_chunks - 1 else self._tail

    def _acquire(self):
        from cloud_tpu.models.decoding import acquire_cache
        engine = self.engine
        cache = _plain(acquire_cache(engine._dense, 1))
        gvec = None
        if self.prefix_len:
            gvec = jnp.asarray(self._gather_vec, jnp.int32)
            cache = engine._gather(cache, engine.cache, gvec,
                                   np.int32(self.prefix_len))
        self._cache = cache
        if engine.spec_on:
            dcache = _plain(acquire_cache(engine._dense_draft, 1))
            if self.prefix_len:
                dcache = engine._gather(dcache, engine.draft_cache,
                                        gvec, np.int32(self.prefix_len))
            self._dcache = dcache

    def step(self):
        """Runs the next chunk. Intermediate chunks return None (cache
        advanced, nothing sampled); the final chunk samples the first
        token and returns the `PrefillResult` — blocking until the
        token is on host, the TTFT point, exactly like `prefill()`."""
        if self._closed:
            raise RuntimeError(
                "ChunkedPrefill already consumed or abandoned.")
        engine = self.engine
        t0_ns = time.monotonic_ns()
        if self._cache is None:
            self._acquire()
        i = self.chunks_done
        C = self.chunk_size
        if i < self.n_chunks - 1:
            tokens = jnp.asarray(self._suffix[None, i * C:(i + 1) * C])
            mask = jnp.ones((1, C), bool)
            self._cache = _cache_prefill_fn(engine._dense)(
                engine._params, self._cache, tokens, mask)
            if engine.spec_on:
                self._dcache = _cache_prefill_fn(engine._dense_draft)(
                    engine._draft_params, self._dcache, tokens, mask)
            self.chunks_done = i + 1
            spans.complete("serve_prefill_chunk", t0_ns,
                           time.monotonic_ns() - t0_ns)
            return None
        tail, bucket = self._tail, self._tail_bucket
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :tail] = self._suffix[i * C:]
        mask = np.zeros((1, bucket), bool)
        mask[0, :tail] = True
        fn = _serve_prefill_fns(
            engine._dense, float(self._sampling["temperature"]),
            self._sampling["top_k"], self._sampling["top_p"])
        pcache, first = fn(engine._params, self._cache,
                           jnp.asarray(tokens), self._prefill_rng,
                           jnp.asarray(mask), np.int32(tail - 1))
        self._cache = None
        dpcache = None
        if engine.spec_on:
            dpcache = _cache_prefill_fn(engine._dense_draft)(
                engine._draft_params, self._dcache,
                jnp.asarray(tokens), jnp.asarray(mask))
            self._dcache = None
        n_steps = self.max_new_tokens
        step_keys = np.zeros((engine.max_new_cap - 1, 2), np.uint32)
        if self._override_rest is not None:
            rest = np.asarray(self._override_rest,
                              np.uint32).reshape(-1, 2)
            if n_steps > 1:
                step_keys[:n_steps - 1] = rest[:n_steps - 1]
        elif n_steps > 1:
            step_keys[:n_steps - 1] = np.asarray(
                jax.random.split(self._key, n_steps - 1))
        first_host = int(runtime.device_fetch(first)[0])
        spans.complete("serve_prefill_chunk", t0_ns,
                       time.monotonic_ns() - t0_ns)
        self.chunks_done = i + 1
        self._closed = True
        return PrefillResult(first_token=first_host, pcache=pcache,
                             dpcache=dpcache, step_keys=step_keys,
                             bucket=bucket, n_steps=n_steps,
                             prompt_len=self.prompt_len)

    def abandon(self):
        """Parks any held dense cache(s) back in the reuse pool (the
        drain/fail path; a consumed prefill's caches belong to its
        PrefillResult and go back via `release_prefill`)."""
        from cloud_tpu.models.decoding import release_cache
        self._closed = True
        if self._cache is not None:
            release_cache(self.engine._dense, 1, self._cache)
            self._cache = None
        if self._dcache is not None:
            release_cache(self.engine._dense_draft, 1, self._dcache)
            self._dcache = None


class DecodeEngine:
    """Continuous-batching decode over `slots` slots of a paged pool.

    Single-owner device state: exactly one thread may call
    `insert`/`tick`/`evict` (the scheduler's tick thread); MISS-path
    `prefill` (prefix_len == 0) is safe to call concurrently from an
    admission thread. HIT-path prefill reads `self.cache`, which the
    tick donates every call — it must run on the tick thread.
    """

    def __init__(self, model, params, slots, page_size, num_pages,
                 max_new_cap=None, draft_model=None, draft_params=None,
                 spec_k=0, page_dtype="", ladder=None):
        from cloud_tpu.models.transformer import TransformerLM

        if not isinstance(model, TransformerLM):
            raise NotImplementedError(
                "graftserve v1 serves TransformerLM (dense causal "
                "attention); got {}.".format(type(model).__name__))
        if model.max_seq_len % page_size:
            raise ValueError(
                "max_seq_len ({}) must be a multiple of page_size "
                "({}).".format(model.max_seq_len, page_size))
        if page_dtype not in ("", "int8"):
            raise ValueError(
                "page_dtype must be '' or 'int8'; got {!r}.".format(
                    page_dtype))
        self.model = model
        self.slots = int(slots)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.pages_per_slot = model.max_seq_len // page_size
        self.max_seq_len = model.max_seq_len
        self.max_new_cap = int(max_new_cap or model.max_seq_len)
        if self.max_new_cap < 2:
            raise ValueError("max_new_cap must be >= 2.")
        # graftflex geometry ladder: the slot counts this engine may
        # resize between. Page tables are pool-indexed, so a resize
        # migrates slot ROWS only (a fixed-shape gather per geometry
        # pair) — KV pages never move and one PagePool serves every
        # rung. A singleton ladder is the fixed-geometry engine.
        ladder = tuple(int(s) for s in (ladder or (self.slots,)))
        if any(s < 1 for s in ladder):
            raise ValueError(
                "ladder rungs must be positive; got {}.".format(ladder))
        if list(ladder) != sorted(set(ladder)):
            raise ValueError(
                "ladder must be strictly increasing; got {}.".format(
                    ladder))
        if len(ladder) > 1 and any(s & (s - 1) for s in ladder):
            raise ValueError(
                "ladder rungs must be powers of two (the pre-warmed "
                "geometry set stays small); got {}.".format(ladder))
        if self.slots not in ladder:
            raise ValueError(
                "initial slots ({}) must be a ladder rung; got "
                "{}.".format(self.slots, ladder))
        self.ladder = ladder
        self._params = params
        self.spec_k = int(spec_k)
        self.spec_on = draft_model is not None and self.spec_k > 0
        # "" = pages in compute_dtype; "int8" = graftpack quantized
        # pages (per-page per-head f32 scale sidecars in the same
        # cache subtrees — models/transformer.py).
        self.page_dtype = str(page_dtype)
        # The SAME decode clone generate() derives, so the engine's
        # dense prefill caches come from the shared reuse pool solo
        # generate() calls in the process also draw from.
        self._dense = model.clone(decode=True, dropout_rate=0.0)
        self._paged = model.clone(decode=True, dropout_rate=0.0,
                                  kv_page_size=page_size,
                                  kv_num_pages=num_pages,
                                  kv_page_dtype=self.page_dtype)

        from cloud_tpu.models.decoding import (best_effort_donation,
                                               empty_cache)
        self.cache = _plain(empty_cache(self._paged, self.slots))

        if self.spec_on:
            if not isinstance(draft_model, TransformerLM):
                raise NotImplementedError(
                    "draft_model must be a TransformerLM; got "
                    "{}.".format(type(draft_model).__name__))
            if draft_model.vocab_size != model.vocab_size:
                raise ValueError(
                    "draft vocab_size ({}) must match target ({}) — "
                    "accept compares token ids.".format(
                        draft_model.vocab_size, model.vocab_size))
            if draft_model.max_seq_len != model.max_seq_len:
                raise ValueError(
                    "draft max_seq_len ({}) must match target ({}) — "
                    "both caches share the page geometry.".format(
                        draft_model.max_seq_len, model.max_seq_len))
            self._draft_params = draft_params
            self._dense_draft = draft_model.clone(decode=True,
                                                  dropout_rate=0.0)
            # Same page_size/num_pages: page id i means the same token
            # span in both pools, so one page table (and one prefix
            # trie) serves target and draft caches.
            self._paged_draft = draft_model.clone(
                decode=True, dropout_rate=0.0, kv_page_size=page_size,
                kv_num_pages=num_pages,
                kv_page_dtype=self.page_dtype)
            self.draft_cache = _plain(
                empty_cache(self._paged_draft, self.slots))
        else:
            self._draft_params = None
            self._dense_draft = None
            self._paged_draft = None
            self.draft_cache = None

        key_width = self.max_new_cap - 1
        self.ctl = {
            "active": jnp.zeros((slots,), jnp.bool_),
            "done": jnp.zeros((slots,), jnp.bool_),
            "cur_tok": jnp.zeros((slots,), jnp.int32),
            "steps_done": jnp.zeros((slots,), jnp.int32),
            "max_steps": jnp.zeros((slots,), jnp.int32),
            "temperature": jnp.zeros((slots,), jnp.float32),
            "top_k": jnp.ones((slots,), jnp.int32),
            "top_p": jnp.ones((slots,), jnp.float32),
            "eos": jnp.zeros((slots,), jnp.int32),
            "has_eos": jnp.zeros((slots,), jnp.bool_),
            "step_keys": jnp.zeros((slots, key_width, 2), jnp.uint32),
        }
        jit = runtime.instrumented_jit
        if self.spec_on:
            self._tick = best_effort_donation(functools.partial(
                jit, donate_argnums=(2, 3, 4))(self._spec_tick_impl))
            self._insert = best_effort_donation(functools.partial(
                jit, donate_argnums=(0, 1, 2))(self._insert_spec_impl))
            self._evict = best_effort_donation(functools.partial(
                jit, donate_argnums=(0, 1, 2))(self._evict_spec_impl))
            self._resize = best_effort_donation(functools.partial(
                jit, donate_argnums=(0, 1, 2))(self._resize_spec_impl))
        else:
            self._tick = best_effort_donation(functools.partial(
                jit, donate_argnums=(1, 2))(self._tick_impl))
            self._insert = best_effort_donation(functools.partial(
                jit, donate_argnums=(0, 1))(self._insert_impl))
            self._evict = best_effort_donation(functools.partial(
                jit, donate_argnums=(0, 1))(self._evict_impl))
            self._resize = best_effort_donation(functools.partial(
                jit, donate_argnums=(0, 1))(self._resize_impl))
        gather_exec = best_effort_donation(functools.partial(
            jit, donate_argnums=(0,))(self._gather_impl))

        def gather(dense_cache, pool_cache, page_vec, prefix_len):
            # The view strips slot-count-bound leaves so the gather
            # signature is identical at every geometry rung.
            return gather_exec(dense_cache, _pool_pages_view(pool_cache),
                               page_vec, prefix_len)

        self._gather = gather
        # Host-tier executables: snapshot READS the pool cache (no
        # donation — the tick keeps it); promote replaces it.
        self._snapshot = jit(self._snapshot_impl)
        self._promote = best_effort_donation(functools.partial(
            jit, donate_argnums=(0,))(self._promote_impl))
        self._warm_stats = None
        self._kernel_costs = {}

    # -- prefill ------------------------------------------------------

    def prefill(self, prompt, max_new_tokens, rng, sampling,
                prefix_len=0, gather_vec=None, key_override=None):
        """Canonical right-pad prefill for one request. `sampling` is a
        normalized dict: temperature (float), top_k (int|None), top_p
        (float|None), eos_token (int|None).

        prefix_len > 0 is a prefix-cache HIT: `gather_vec` (a
        pool.page_vec covering ceil(prefix_len / page_size) resident
        pages) seeds the dense cache with the first `prefix_len`
        cached positions, and the model runs over the suffix only.
        The rng schedule is unchanged — prefix reuse never moves a
        sample draw, which is the bit-identity contract.

        `key_override=(prefill_key, step_keys_rest)` is the graftstorm
        requeue hook: instead of deriving the schedule by splitting
        `rng`, the prefill samples with the exact uint32[2] key the
        faulted run would have used for this position and arms the
        remaining original schedule (shifted so the continuation's
        first tick reads row 0). That re-bases a request interrupted
        after n tokens onto keys n, n+1, ... of its original split —
        the per-slot graftguard resume discipline, so the continuation
        completes bit-identical to the uninterrupted decode.

        Returns a `PrefillResult`; blocks until the first token is on
        host (the TTFT point)."""
        from cloud_tpu.models.decoding import (acquire_cache,
                                               bucket_length)

        t0_ns = time.monotonic_ns()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        prompt_len = int(prompt.shape[0])
        prefix_len = int(prefix_len)
        if not 0 <= prefix_len < prompt_len:
            raise ValueError(
                "prefix_len must be in [0, prompt_len); got {} for a "
                "{}-token prompt.".format(prefix_len, prompt_len))
        n_suffix = prompt_len - prefix_len
        bucket = bucket_length(n_suffix, self.max_seq_len)
        if prefix_len + bucket > self.max_seq_len:
            raise ValueError(
                "prefix ({}) + suffix bucket ({}) exceeds max_seq_len "
                "{}; the scheduler trims the match to keep the padded "
                "suffix in-cache.".format(prefix_len, bucket,
                                          self.max_seq_len))
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n_suffix] = prompt[prefix_len:]
        mask = np.zeros((1, bucket), bool)
        mask[0, :n_suffix] = True
        if key_override is None:
            key, prefill_rng = jax.random.split(rng)
        else:
            # Same aval as a split key row (uint32[2], the legacy raw
            # key layout categorical accepts), so the override path
            # reuses the warmed prefill executable — no retrace.
            prefill_rng = jnp.asarray(key_override[0], jnp.uint32)
            key = None

        cache = _plain(acquire_cache(self._dense, 1))
        gvec = None
        if prefix_len:
            gvec = jnp.asarray(gather_vec, jnp.int32)
            cache = self._gather(cache, self.cache, gvec,
                                 np.int32(prefix_len))
        fn = _serve_prefill_fns(
            self._dense, float(sampling["temperature"]),
            sampling["top_k"], sampling["top_p"])
        pcache, first = fn(self._params, cache, jnp.asarray(tokens),
                           prefill_rng, jnp.asarray(mask),
                           np.int32(n_suffix - 1))
        dpcache = None
        if self.spec_on:
            dcache = _plain(acquire_cache(self._dense_draft, 1))
            if prefix_len:
                dcache = self._gather(dcache, self.draft_cache, gvec,
                                      np.int32(prefix_len))
            dpcache = _cache_prefill_fn(self._dense_draft)(
                self._draft_params, dcache, jnp.asarray(tokens),
                jnp.asarray(mask))
        step_keys = np.zeros((self.max_new_cap - 1, 2), np.uint32)
        if key_override is not None:
            rest = np.asarray(key_override[1], np.uint32).reshape(-1, 2)
            if max_new_tokens > 1:
                step_keys[:max_new_tokens - 1] = \
                    rest[:max_new_tokens - 1]
        elif max_new_tokens > 1:
            step_keys[:max_new_tokens - 1] = np.asarray(
                jax.random.split(key, max_new_tokens - 1))
        first_host = int(runtime.device_fetch(first)[0])
        # Span covers gather + dense prefill + the blocking first-token
        # fetch — the device side of TTFT (no-op with no tracer).
        spans.complete("serve_prefill", t0_ns,
                       time.monotonic_ns() - t0_ns)
        return PrefillResult(first_token=first_host, pcache=pcache,
                             dpcache=dpcache, step_keys=step_keys,
                             bucket=bucket, n_steps=int(max_new_tokens),
                             prompt_len=prompt_len)

    def prefill_chunks(self, prompt, max_new_tokens, rng, sampling,
                       chunk_size, prefix_len=0, gather_vec=None,
                       key_override=None):
        """Chunked-prefill continuation for one request: the suffix
        runs as `chunk_plan()` windows — fixed `chunk_size` chunks
        through the cache-only executable, then a pow2-bucketed tail
        through the SAME sampling executable a whole prefill of that
        suffix would use. `prefix_len`/`gather_vec` seed the first
        chunk's start offset (prefix-cache hit) and `key_override`
        re-bases a requeued continuation, both exactly as `prefill()`.
        Returns a `ChunkedPrefill`; no device work happens until its
        first `step()` (which must run on the tick thread when
        `prefix_len > 0` — the gather reads the tick-donated pool
        cache)."""
        chunk_size = int(chunk_size)
        if chunk_size < 1 or chunk_size & (chunk_size - 1):
            raise ValueError(
                "chunk_size must be a power of two >= 1 (the pow2 "
                "bound keeps chunked writes inside the whole-prefill "
                "bucket); got {}.".format(chunk_size))
        if chunk_size > self.max_seq_len:
            raise ValueError(
                "chunk_size ({}) exceeds max_seq_len ({}).".format(
                    chunk_size, self.max_seq_len))
        return ChunkedPrefill(self, prompt, max_new_tokens, rng,
                              sampling, chunk_size,
                              prefix_len=prefix_len,
                              gather_vec=gather_vec,
                              key_override=key_override)

    def release_prefill(self, result):
        """Parks a consumed (or abandoned) prefill's dense cache(s)
        back in the decode-cache reuse pool."""
        from cloud_tpu.models.decoding import release_cache
        release_cache(self._dense, 1, result.pcache)
        result.pcache = None
        if result.dpcache is not None:
            release_cache(self._dense_draft, 1, result.dpcache)
            result.dpcache = None

    # -- slot ops (tick thread) ---------------------------------------

    def insert(self, slot, result, page_vec, scatter_vec, sampling):
        """Writes a prefilled request into free slot `slot`. The page
        vectors split ownership: `page_vec` is the slot's logical page
        table (shared prefix pages included); `scatter_vec` routes
        chunk i to page_vec[i] where the slot OWNS the page (fresh
        pages, including the copy-on-write page a mid-page divergence
        reconstructs) and to the scratch page 0 where the content is
        already resident and shared. One fixed-shape executable for
        every bucket — the prefill cache is always full-length dense.
        """
        vocab = self.model.vocab_size
        top_k = sampling["top_k"]
        top_p = sampling["top_p"]
        eos = sampling["eos_token"]
        args = (np.int32(slot), jnp.asarray(page_vec, jnp.int32),
                jnp.asarray(scatter_vec, jnp.int32),
                jnp.asarray(result.step_keys),
                np.int32(result.n_steps), np.int32(result.first_token),
                np.float32(sampling["temperature"]),
                np.int32(vocab if top_k is None else top_k),
                np.float32(1.0 if top_p is None else top_p),
                np.int32(0 if eos is None else eos),
                bool(eos is not None))
        if self.spec_on:
            self.cache, self.draft_cache, self.ctl = self._insert(
                self.cache, self.draft_cache, self.ctl,
                _plain(result.pcache), _plain(result.dpcache), *args)
        else:
            self.cache, self.ctl = self._insert(
                self.cache, self.ctl, _plain(result.pcache), *args)
        self.release_prefill(result)

    def tick(self):
        """Advances every active slot. Plain engine: one token per
        call, device out-array `[2, S]` (row 0: sampled token, row 1:
        finished flag). Speculative engine: up to spec_k + 1 tokens per
        call, out-array `[spec_k + 4, S]` — rows 0..spec_k committed
        tokens (-1 on inactive slots), row spec_k + 1 the commit count,
        row spec_k + 2 the finished flag, row spec_k + 3 the accepted
        draft count (-1 on non-speculating slots). The scheduler
        fetches it with `runtime.device_fetch`."""
        if self.spec_on:
            (self.cache, self.draft_cache, self.ctl, out) = self._tick(
                self._params, self._draft_params, self.cache,
                self.draft_cache, self.ctl)
        else:
            self.cache, self.ctl, out = self._tick(
                self._params, self.cache, self.ctl)
        return out

    def evict(self, evict_mask):
        """Frees every slot where `evict_mask` is True: page-table and
        validity rows go back to scratch/zero, the control row disarms.
        The physical page ids go back to the host pool separately
        (scheduler bookkeeping)."""
        if self.spec_on:
            self.cache, self.draft_cache, self.ctl = self._evict(
                self.cache, self.draft_cache, self.ctl,
                jnp.asarray(evict_mask, bool))
        else:
            self.cache, self.ctl = self._evict(
                self.cache, self.ctl, jnp.asarray(evict_mask, bool))

    def resize(self, new_slots, perm):
        """Moves the engine to ladder rung `new_slots` at a tick
        boundary. `perm` is int32 `[new_slots]`: new slot i takes old
        slot `perm[i]`'s rows (-1 = empty). Geometry-BOUND state only
        moves — page tables, validity, positions, and the control rows
        (rng schedules, eos latches, step counters) gather through one
        fixed-shape executable per (old, new) pair; the KV pages (and
        the draft twin's, under the same perm) stay exactly where they
        are in the shared pool. In-flight slots therefore continue
        bit-identical: their step_keys rows, steps_done counters and
        done/eos latches ride the gather unchanged. Tick thread only —
        must run between ticks, never mid-tick."""
        new_slots = int(new_slots)
        if new_slots not in self.ladder:
            raise ValueError(
                "resize target {} is not a ladder rung {}.".format(
                    new_slots, self.ladder))
        perm = np.asarray(perm, np.int32).reshape(-1)
        if perm.shape[0] != new_slots:
            raise ValueError(
                "perm must have {} rows; got {}.".format(
                    new_slots, perm.shape[0]))
        live = perm[perm >= 0]
        if (perm >= self.slots).any() or len(set(live.tolist())) \
                != live.shape[0]:
            raise ValueError(
                "perm rows must be -1 or unique old-slot indices "
                "< {}; got {}.".format(self.slots, perm.tolist()))
        pv = jnp.asarray(perm, jnp.int32)
        if self.spec_on:
            self.cache, self.draft_cache, self.ctl = self._resize(
                self.cache, self.draft_cache, self.ctl, pv)
        else:
            self.cache, self.ctl = self._resize(self.cache, self.ctl,
                                                pv)
        self.slots = new_slots

    # -- retrace sentinel ---------------------------------------------

    def mark_warm(self):
        """Snapshots the compile counters; `check_no_retrace()` raises
        on any growth after this point. Also arms graftsan's GS005
        retrace-attribution: under a `sanitize()` scope, any trace
        after this mark is reported with the exact signature leaf
        whose avals moved, not just a count."""
        self._warm_stats = runtime.compile_stats()
        runtime.notify_warm_mark()

    def check_no_retrace(self):
        if self._warm_stats is None:
            return
        now = runtime.compile_stats()
        grew = {k: now[k] - self._warm_stats[k]
                for k in ("n_traces", "n_compiles")
                if now[k] > self._warm_stats[k]}
        if grew:
            raise RetraceError(
                "serving path traced/compiled after warm-up: {} "
                "(static-shape leak).".format(grew))

    def kernel_costs(self, slots=None):
        """Per-TICK cost rows for the telemetry kernel gauges: the
        paged-attention flops / bytes-moved one tick dispatches (all
        layers, verify-window width when speculating), from the jit
        cost-analysis hook in ops/paged_attention.py. Computed lazily
        (one uninstrumented lowering — the retrace sentinel counts only
        `instrumented_jit` sites) and cached PER GEOMETRY: a tick's
        cost scales with its slot count, so A/B rows from different
        ladder rungs must never share one entry. Defaults to the
        current rung; the scheduler pairs the rows with the measured
        tick latency for the pct_peak gauge."""
        slots = int(self.slots if slots is None else slots)
        if slots not in self._kernel_costs:
            from cloud_tpu import ops

            model = self.model
            head_dim = model.d_model // model.num_heads
            seq = self.spec_k + 1 if self.spec_on else 1
            cost = ops.paged_attention_cost(
                slots, seq, model.num_heads, head_dim,
                self.page_size, self.pages_per_slot,
                dtype=model.compute_dtype,
                kv_dtype=(jnp.int8 if self.page_dtype == "int8"
                          else None))
            layers = model.num_layers
            self._kernel_costs[slots] = {
                "paged_attention": {
                    "flops": cost["flops"] * layers,
                    "bytes_moved": cost["bytes_moved"] * layers,
                },
            }
        return self._kernel_costs[slots]

    # -- jitted bodies ------------------------------------------------

    def _gather_impl(self, dense_cache, pool_cache, page_vec,
                     prefix_len):
        """Seeds a fresh dense [1, L] cache with the first `prefix_len`
        positions of the pool pages in `page_vec` (a full page_vec —
        [pages_per_slot], scratch-padded past the match). The invalid
        tail is zeroed, so the seeded cache is bitwise the cache a
        right-pad prefill of those `prefix_len` tokens would have
        produced — the suffix prefill continues from it exactly as if
        the whole prompt had been run."""
        L = self.max_seq_len
        valid = jnp.arange(L) < prefix_len

        def seed(att, datt):
            out = dict(datt)
            k = att["key_pages"][page_vec]   # [ppn, P, H, D]
            v = att["value_pages"][page_vec]
            if "key_scales" in att:
                # Int8 pool -> dense compute-dtype cache: dequantize
                # with the per-page per-head scales (never-written
                # pages carry scale 0 -> exact zeros).
                ks = att["key_scales"][page_vec][:, None, :, None]
                vs = att["value_scales"][page_vec][:, None, :, None]
                k = (k.astype(jnp.float32) * ks).astype(
                    datt["cached_key"].dtype)
                v = (v.astype(jnp.float32) * vs).astype(
                    datt["cached_value"].dtype)
            k = k.reshape(1, L, *k.shape[2:])
            v = v.reshape(1, L, *v.shape[2:])
            out["cached_key"] = jnp.where(
                valid[None, :, None, None], k, jnp.zeros((), k.dtype))
            out["cached_value"] = jnp.where(
                valid[None, :, None, None], v, jnp.zeros((), v.dtype))
            out["cache_index"] = prefix_len.astype(jnp.int32)
            out["slot_valid"] = valid[None]
            out["slot_pos"] = jnp.where(
                valid, jnp.arange(L, dtype=jnp.int32), 0)[None]
            out["token_count"] = jnp.full((1,), prefix_len, jnp.int32)
            return out

        result = _map_attention(pool_cache, seed, dense_cache)
        # _map_attention keeps non-attention leaves from its FIRST
        # tree; the only one is pos_count, stripped to None by the
        # caller's _pool_pages_view (its pool shape [slots] would bind
        # the geometry) — install the dense [1] counter at the prefix
        # depth.
        result["pos_count"] = jnp.full((1,), prefix_len, jnp.int32)
        return result

    def _scatter_request(self, cache, pcache, slot, page_vec,
                         scatter_vec):
        """One request's dense prefill cache into the paged pool:
        chunk i of the [1, L] dense view goes to scatter_vec[i] (its
        fresh page, or scratch when shared content is already there);
        the page table gets page_vec. slot_steps comes from
        token_count (REAL tokens — cache_index includes the right-pad,
        which must be overwritten by decode writes, not skipped).

        Int8 pools quantize here, per chunk per head: invalid (right-
        pad) positions are zeroed BEFORE the amax so pad garbage never
        inflates a page's scale, and each owned page's scale resets to
        its chunk amax / 127 — which is what makes recycled pages'
        stale scales unobservable (every owned page passes through
        this scatter or the promote before a decode write can grow its
        scale)."""
        ppn, page = self.pages_per_slot, self.page_size

        def scatter(att, patt):
            out = dict(att)
            chunks_k = patt["cached_key"][0].reshape(
                ppn, page, *patt["cached_key"].shape[2:])
            chunks_v = patt["cached_value"][0].reshape(
                ppn, page, *patt["cached_value"].shape[2:])
            if "key_scales" in att:
                vm = patt["slot_valid"][0].astype(jnp.float32).reshape(
                    ppn, page)[:, :, None, None]

                def quant(chunks):
                    cf = chunks.astype(jnp.float32) * vm
                    amax = jnp.max(jnp.abs(cf), axis=(1, 3))  # [ppn,H]
                    scale = amax / 127.0
                    safe = jnp.where(scale > 0, scale, 1.0)
                    q = jnp.clip(jnp.round(cf / safe[:, None, :, None]),
                                 -127, 127).astype(jnp.int8)
                    return q, scale

                chunks_k, scale_k = quant(chunks_k)
                chunks_v, scale_v = quant(chunks_v)
                out["key_scales"] = att["key_scales"].at[
                    scatter_vec].set(scale_k)
                out["value_scales"] = att["value_scales"].at[
                    scatter_vec].set(scale_v)
            # Owned ids are unique and nonzero, so fresh chunks land
            # exactly; shared/overflow chunks collapse onto scratch,
            # whose content is never attended.
            out["key_pages"] = att["key_pages"].at[scatter_vec].set(
                chunks_k)
            out["value_pages"] = att["value_pages"].at[scatter_vec].set(
                chunks_v)
            out["page_table"] = att["page_table"].at[slot].set(page_vec)
            out["slot_steps"] = att["slot_steps"].at[slot].set(
                patt["token_count"][0])
            out["slot_valid"] = att["slot_valid"].at[slot].set(
                patt["slot_valid"][0])
            return out

        new_cache = _map_attention(cache, scatter, pcache)
        new_cache["pos_count"] = cache["pos_count"].at[slot].set(
            pcache["pos_count"][0])
        return new_cache

    def _arm_ctl(self, ctl, slot, step_keys_row, max_steps, first_tok,
                 temperature, top_k, top_p, eos, has_eos):
        out_ctl = dict(ctl)
        out_ctl["active"] = ctl["active"].at[slot].set(True)
        out_ctl["done"] = ctl["done"].at[slot].set(
            has_eos & (first_tok == eos))
        out_ctl["cur_tok"] = ctl["cur_tok"].at[slot].set(first_tok)
        out_ctl["steps_done"] = ctl["steps_done"].at[slot].set(1)
        out_ctl["max_steps"] = ctl["max_steps"].at[slot].set(max_steps)
        out_ctl["temperature"] = ctl["temperature"].at[slot].set(
            temperature)
        out_ctl["top_k"] = ctl["top_k"].at[slot].set(top_k)
        out_ctl["top_p"] = ctl["top_p"].at[slot].set(top_p)
        out_ctl["eos"] = ctl["eos"].at[slot].set(eos)
        out_ctl["has_eos"] = ctl["has_eos"].at[slot].set(has_eos)
        out_ctl["step_keys"] = ctl["step_keys"].at[slot].set(
            step_keys_row)
        return out_ctl

    def _insert_impl(self, cache, ctl, pcache, slot, page_vec,
                     scatter_vec, step_keys_row, max_steps, first_tok,
                     temperature, top_k, top_p, eos, has_eos):
        new_cache = self._scatter_request(cache, pcache, slot, page_vec,
                                          scatter_vec)
        out_ctl = self._arm_ctl(ctl, slot, step_keys_row, max_steps,
                                first_tok, temperature, top_k, top_p,
                                eos, has_eos)
        return new_cache, out_ctl

    def _insert_spec_impl(self, cache, dcache, ctl, pcache, dpcache,
                          slot, page_vec, scatter_vec, step_keys_row,
                          max_steps, first_tok, temperature, top_k,
                          top_p, eos, has_eos):
        new_cache = self._scatter_request(cache, pcache, slot, page_vec,
                                          scatter_vec)
        new_dcache = self._scatter_request(dcache, dpcache, slot,
                                           page_vec, scatter_vec)
        out_ctl = self._arm_ctl(ctl, slot, step_keys_row, max_steps,
                                first_tok, temperature, top_k, top_p,
                                eos, has_eos)
        return new_cache, new_dcache, out_ctl

    def _tick_impl(self, params, cache, ctl):
        active = ctl["active"]
        logits, vars_ = self._paged.apply(
            {"params": params, "cache": cache},
            ctl["cur_tok"][:, None], active[:, None], mutable=["cache"])
        logits = logits[:, 0]  # [S, V]
        # Slot s's step i consumes generate()'s step_rngs[i]; after
        # insertion steps_done is 1 (the prefill token), so the first
        # tick reads key row 0.
        key_idx = jnp.clip(ctl["steps_done"] - 1, 0,
                           ctl["step_keys"].shape[1] - 1)
        keys = jnp.take_along_axis(
            ctl["step_keys"], key_idx[:, None, None], 1)[:, 0]
        # Inactive slots keep their stale sampling rows; zeroing the
        # temperature they feed the sampler keeps the greedy fast path
        # available whenever the LIVE traffic is all-greedy.
        live_temp = jnp.where(active, ctl["temperature"], 0.0)
        nxt = _sample_slots(logits, keys, live_temp, ctl["top_k"],
                            ctl["top_p"])
        latched = ctl["has_eos"] & ctl["done"]
        nxt = jnp.where(latched, ctl["eos"], nxt)
        done = ctl["done"] | (ctl["has_eos"] & (nxt == ctl["eos"]))
        steps = ctl["steps_done"] + active.astype(jnp.int32)
        finished = active & (done | (steps >= ctl["max_steps"]))
        out_ctl = dict(ctl)
        out_ctl["cur_tok"] = jnp.where(active, nxt, ctl["cur_tok"])
        out_ctl["done"] = jnp.where(active, done, ctl["done"])
        out_ctl["steps_done"] = steps
        out = jnp.stack([jnp.where(active, nxt, -1),
                         finished.astype(jnp.int32)])
        return _plain(vars_["cache"]), out_ctl, out

    def _spec_tick_impl(self, params, draft_params, cache, dcache, ctl):
        """Draft/verify speculation, one executable per tick:

          1. draft scan: k greedy single-token steps from cur_tok
             (writes k draft-cache entries per active slot);
          2. verify: ONE (k+1)-token target forward over
             [cur_tok, d_1..d_k] (writes k+1 target-cache entries);
          3. accept: greedy slots keep the longest draft prefix that
             matches the target argmax chain plus the target's own
             next token (`greedy_accept` — speculative.py's pinned
             math); sampled slots commit one token from position 0,
             whose logits are bitwise the plain tick's;
          4. rewind: both caches roll back to exactly
             prompt + steps' - 1 entries (`paged_slot_rewind`); a
             fully-accepted slot's draft cache is one entry SHORT, so
             a masked catch-up draft forward writes d_k's entry.

        Invariant, before and after every tick: target and draft
        caches both hold `prompt_len + steps_done - 1` entries —
        cur_tok is never in either cache (it is the next input).
        """
        from cloud_tpu.models.decoding import paged_slot_rewind
        from cloud_tpu.models.speculative import greedy_accept

        k = self.spec_k
        # Width from the traced aval, not self.slots: the ladder
        # retraces this body once per rung, and the host attribute may
        # already point at the NEXT rung while a cached executable
        # replays an earlier one.
        slots = ctl["active"].shape[0]
        active = ctl["active"]
        mask1 = active[:, None]

        def draft_step(carry, _):
            dc, tok = carry
            dlogits, dvars = self._paged_draft.apply(
                {"params": draft_params, "cache": dc},
                tok[:, None], mask1, mutable=["cache"])
            nxt = jnp.argmax(dlogits[:, 0].astype(jnp.float32),
                             axis=-1).astype(jnp.int32)
            return (_plain(dvars["cache"]), nxt), nxt

        (dcache, _), drafts = jax.lax.scan(
            draft_step, (dcache, ctl["cur_tok"]), None, length=k)
        drafts = jnp.transpose(drafts, (1, 0))  # [S, k]

        verify_in = jnp.concatenate(
            [ctl["cur_tok"][:, None], drafts], axis=1)  # [S, k+1]
        maskk = jnp.broadcast_to(mask1, (slots, k + 1))
        logits, vars_ = self._paged.apply(
            {"params": params, "cache": cache},
            verify_in, maskk, mutable=["cache"])
        cache = _plain(vars_["cache"])
        greedy = jnp.argmax(logits.astype(jnp.float32),
                            axis=-1).astype(jnp.int32)  # [S, k+1]
        n_acc = greedy_accept(drafts, greedy)  # [S]

        # Sampled (temperature > 0) slots ride the same executable
        # committing ONE token from position 0 — the plain tick's
        # sampler over the plain tick's logits, key schedule included.
        key_idx = jnp.clip(ctl["steps_done"] - 1, 0,
                           ctl["step_keys"].shape[1] - 1)
        keys = jnp.take_along_axis(
            ctl["step_keys"], key_idx[:, None, None], 1)[:, 0]
        live_temp = jnp.where(active, ctl["temperature"], 0.0)
        sampled0 = _sample_slots(logits[:, 0], keys, live_temp,
                                 ctl["top_k"], ctl["top_p"])

        is_spec = active & (ctl["temperature"] == 0.0)
        n_acc = jnp.where(is_spec, n_acc, 0)
        bonus = jnp.take_along_axis(greedy, n_acc[:, None], 1)[:, 0]
        pick = jnp.where(is_spec, bonus, sampled0)
        committed = jnp.concatenate(
            [drafts, jnp.zeros((slots, 1), jnp.int32)], axis=1)
        committed = committed.at[jnp.arange(slots), n_acc].set(pick)
        latched = ctl["has_eos"] & ctl["done"]
        committed = jnp.where(latched[:, None], ctl["eos"][:, None],
                              committed)

        base_c = jnp.where(is_spec, n_acc + 1, 1)
        # Commit stops at the first eos: tokens past it are never
        # emitted (the scheduler latch-fills the tail on completion,
        # exactly generate()'s where(done, eos, ...) behavior).
        eos_hit = (ctl["has_eos"][:, None]
                   & (committed == ctl["eos"][:, None]))
        hit_idx = jnp.where(eos_hit, jnp.arange(k + 1)[None, :], k + 1)
        first_eos = jnp.min(hit_idx, axis=1)
        c = jnp.minimum(base_c, first_eos + 1)
        done_new = ctl["done"] | (ctl["has_eos"] & (first_eos < base_c))
        steps = ctl["steps_done"] + jnp.where(active, c, 0)
        finished = active & (done_new | (steps >= ctl["max_steps"]))
        cur_tok = committed[jnp.arange(slots), jnp.maximum(c - 1, 0)]

        # Rewind both caches to prompt + steps' - 1 entries. Target
        # wrote k+1 and keeps c; draft wrote k and keeps c, except the
        # full-accept slot (c == k+1) which is one SHORT — the masked
        # catch-up forward below writes d_k's missing entry (mask 0
        # slots neither move their pointers nor validate anything).
        delta_t = jnp.where(active, k + 1 - c, 0)
        cache = paged_slot_rewind(cache, delta_t, self.max_seq_len)
        cache["pos_count"] = cache["pos_count"] - delta_t
        delta_d = jnp.where(active, jnp.maximum(k - c, 0), 0)
        dcache = paged_slot_rewind(dcache, delta_d, self.max_seq_len)
        dcache["pos_count"] = dcache["pos_count"] - delta_d
        catch = active & (c == k + 1)
        _, dvars = self._paged_draft.apply(
            {"params": draft_params, "cache": dcache},
            drafts[:, k - 1][:, None], catch[:, None],
            mutable=["cache"])
        dcache = _plain(dvars["cache"])

        out_ctl = dict(ctl)
        out_ctl["cur_tok"] = jnp.where(active, cur_tok, ctl["cur_tok"])
        out_ctl["done"] = jnp.where(active, done_new, ctl["done"])
        out_ctl["steps_done"] = steps
        out = jnp.concatenate([
            jnp.where(active[None, :], jnp.transpose(committed), -1),
            jnp.where(active, c, 0)[None, :],
            finished.astype(jnp.int32)[None, :],
            jnp.where(is_spec, n_acc, -1)[None, :],
        ], axis=0)  # [k+4, S]
        return cache, dcache, out_ctl, out

    def _snapshot_impl(self, cache, page_vec):
        """Per-attention-layer K/V page blocks (+ scale sidecars) for
        `page_vec` ([pages_per_slot] int32, scratch-padded) — the
        device half of a host-tier demote. Reads the pool cache, never
        donates it; one fixed-shape executable for any page count."""
        def snap(att):
            entry = {"key_pages": att["key_pages"][page_vec],
                     "value_pages": att["value_pages"][page_vec]}
            if "key_scales" in att:
                entry["key_scales"] = att["key_scales"][page_vec]
                entry["value_scales"] = att["value_scales"][page_vec]
            return entry

        tree = _map_attention(cache, snap)
        tree.pop("pos_count", None)
        return tree

    def _promote_impl(self, cache, host_tree, page_vec):
        """Scatters a host-tier entry's page blocks back into the pool
        at `page_vec` (full-width, scratch-padded past the promoted
        extension — padded rows collapse onto scratch exactly like the
        insert scatter's shared chunks)."""
        def prom(att, h):
            out = dict(att)
            out["key_pages"] = att["key_pages"].at[page_vec].set(
                h["key_pages"])
            out["value_pages"] = att["value_pages"].at[page_vec].set(
                h["value_pages"])
            if "key_scales" in att:
                out["key_scales"] = att["key_scales"].at[page_vec].set(
                    h["key_scales"])
                out["value_scales"] = att["value_scales"].at[
                    page_vec].set(h["value_scales"])
            return out

        # The snapshot strips pos_count (it is slot state, not page
        # content); put a placeholder back so the parallel walk indexes
        # the same top-level keys the cache has.
        host_tree = dict(host_tree)
        host_tree.setdefault("pos_count", 0)
        return _map_attention(cache, prom, host_tree)

    # -- host page tier (tick thread) ---------------------------------

    def snapshot_pages(self, page_ids):
        """Host numpy snapshot of `page_ids`' pool content (the demote
        D2H): a pytree mirroring the cache's attention subtrees, each
        holding `[n, P, H, D]` K/V blocks (+ `[n, H]` scales in int8
        mode) with n == len(page_ids), rows in logical page order.
        Tick thread only — reads the tick-donated cache."""
        n = len(page_ids)
        vec = jnp.asarray(self.pool_page_vec(page_ids), jnp.int32)
        tree = jax.device_get(self._snapshot(self.cache, vec))
        return jax.tree_util.tree_map(lambda a: a[:n], tree)

    def promote_pages(self, host_tree, page_ids, n_skip=0):
        """Writes a host-tier snapshot back into the pool (the promote
        H2D): logical page i of `host_tree` lands in physical page
        `page_ids[i]`, except the first `n_skip` logical pages (already
        resident via the prefix trie) and any `page_ids` entry of 0,
        which collapse onto scratch. Tick thread only."""
        vec = self.pool_page_vec(page_ids)
        vec[:n_skip] = 0
        n = len(page_ids)
        ppn = self.pages_per_slot

        def pad(a):
            if a.shape[0] == ppn:
                return a
            widths = [(0, ppn - n)] + [(0, 0)] * (a.ndim - 1)
            return np.pad(np.asarray(a), widths)

        padded = jax.tree_util.tree_map(pad, host_tree)
        self.cache = self._promote(self.cache, padded,
                                   jnp.asarray(vec, jnp.int32))

    def pool_page_vec(self, page_ids):
        """Full-width scratch-padded page vector (kvpool.page_vec's
        layout) — kept here so engine-level callers don't need the
        pool object."""
        vec = np.zeros((self.pages_per_slot,), np.int32)
        vec[:len(page_ids)] = page_ids
        return vec

    def page_hbm_bytes(self):
        """HBM bytes ONE physical page costs summed over every
        attention layer (K + V blocks, plus the f32 scale sidecars in
        int8 mode; the draft pool included when speculating — it keys
        on the same page ids). Feeds PagePool.page_bytes for the
        KV-hierarchy gauges."""
        def per_model(m):
            head_dim = m.d_model // m.num_heads
            item = (1 if self.page_dtype == "int8"
                    else jnp.dtype(m.compute_dtype).itemsize)
            per_layer = 2 * self.page_size * m.num_heads * head_dim \
                * item
            if self.page_dtype == "int8":
                per_layer += 2 * m.num_heads * 4
            return per_layer * m.num_layers

        total = per_model(self.model)
        if self.spec_on:
            total += per_model(self._paged_draft)
        return int(total)

    def _clear_slots(self, cache, keep):
        def clear(att):
            out = dict(att)
            out["page_table"] = jnp.where(keep[:, None],
                                          att["page_table"], 0)
            out["slot_steps"] = jnp.where(keep, att["slot_steps"], 0)
            out["slot_valid"] = att["slot_valid"] & keep[:, None]
            return out

        new_cache = _map_attention(cache, clear)
        new_cache["pos_count"] = jnp.where(keep, cache["pos_count"], 0)
        return new_cache

    def _evict_impl(self, cache, ctl, evict_mask):
        keep = ~evict_mask
        new_cache = self._clear_slots(cache, keep)
        out_ctl = dict(ctl)
        out_ctl["active"] = ctl["active"] & keep
        out_ctl["done"] = ctl["done"] & keep
        out_ctl["steps_done"] = jnp.where(keep, ctl["steps_done"], 0)
        out_ctl["cur_tok"] = jnp.where(keep, ctl["cur_tok"], 0)
        out_ctl["max_steps"] = jnp.where(keep, ctl["max_steps"], 0)
        return new_cache, out_ctl

    def _evict_spec_impl(self, cache, dcache, ctl, evict_mask):
        new_cache, out_ctl = self._evict_impl(cache, ctl, evict_mask)
        new_dcache = self._clear_slots(dcache, ~evict_mask)
        return new_cache, new_dcache, out_ctl

    def _resize_slots(self, cache, perm):
        """Geometry-bound slot rows gathered to the new width; the
        page arrays flow through donated and untouched. An empty new
        row (perm -1, src clipped to 0) zeroes exactly the leaves
        `_evict_impl` zeroes, so a fresh rung looks like freshly
        evicted slots."""
        mask = perm >= 0
        src = jnp.clip(perm, 0)

        def rs(att):
            out = dict(att)
            out["page_table"] = jnp.where(mask[:, None],
                                          att["page_table"][src], 0)
            out["slot_steps"] = jnp.where(mask, att["slot_steps"][src],
                                          0)
            out["slot_valid"] = att["slot_valid"][src] & mask[:, None]
            return out

        new_cache = _map_attention(cache, rs)
        new_cache["pos_count"] = jnp.where(mask,
                                           cache["pos_count"][src], 0)
        return new_cache

    def _resize_ctl(self, ctl, perm):
        """Control rows under the same perm. The masked leaves mirror
        `_evict_impl`'s zeroing; sampling config / eos / step_keys rows
        gather unmasked — evict leaves them stale too, and a clipped
        src just copies a real row's staleness. In-flight rows carry
        their exact rng schedule, latch and counters, which is the
        bit-identity contract across a resize."""
        mask = perm >= 0
        src = jnp.clip(perm, 0)
        out_ctl = {k: v[src] for k, v in ctl.items()}
        out_ctl["active"] = ctl["active"][src] & mask
        out_ctl["done"] = ctl["done"][src] & mask
        out_ctl["steps_done"] = jnp.where(mask, ctl["steps_done"][src],
                                          0)
        out_ctl["cur_tok"] = jnp.where(mask, ctl["cur_tok"][src], 0)
        out_ctl["max_steps"] = jnp.where(mask, ctl["max_steps"][src], 0)
        return out_ctl

    def _resize_impl(self, cache, ctl, perm):
        return (self._resize_slots(cache, perm),
                self._resize_ctl(ctl, perm))

    def _resize_spec_impl(self, cache, dcache, ctl, perm):
        return (self._resize_slots(cache, perm),
                self._resize_slots(dcache, perm),
                self._resize_ctl(ctl, perm))


__all__ = ["ChunkedPrefill", "DecodeEngine", "PrefillResult",
           "RetraceError", "chunk_plan"]
