"""Learned TTFT admission predictor fit offline from the reqtrace corpus.

The scheduler's admission controller must answer one question before a request
is allowed to queue: *if admitted now, when does its first token land?*  Until
this module existed the answer came from two live histograms (prefill p50 and
tick p50) accumulated since process start — blind for the first few dozen
requests after a restart, and blind to bucket-dependent prefill cost.  But the
graftlens reqtrace corpus already records exact per-phase ground truth for
every historical request: ``prefill`` events carry the padded bucket and the
measured duration, ``prefill_chunk`` events carry per-chunk durations,
``complete`` events carry end-to-end latency + TTFT + token count, and
``pages_reserved`` events carry the page-reservation wait.  This module fits a
small per-phase quantile model from that corpus and serves predictions that
mirror the live heuristic's phase arithmetic exactly — so the scheduler can
swap it in without changing admission semantics, and fall back to the
histogram heuristic whenever the model is absent or a phase is missing.

Fit offline, load at serve time::

    python -m cloud_tpu.serving.admission fit --trace /var/logs/reqtrace \\
        --out admission_model.json
    python -m cloud_tpu.serving.admission show --model admission_model.json

    CLOUD_TPU_SERVE_ADMISSION_MODEL=admission_model.json  # read at start()

Model shape (``cloud_tpu.admission_model.v1`` JSON):

* ``prefill`` — median prefill seconds as a linear function of the padded
  prompt bucket.  Fit as a binned quantile regression: samples are grouped by
  bucket, the q=0.5 quantile is taken per bin, and a count-weighted least
  squares line is fit through the bin quantiles.  Deterministic, exact on
  clean corpora, and robust to bucket imbalance (each bucket contributes its
  own quantile, not its raw sample mass).
* ``prefill_chunk`` — scalar q=0.5 of per-chunk prefill seconds (chunks are
  fixed-shape, so duration does not depend on the prompt).
* ``token`` — scalar q=0.5 of steady-state seconds-per-token, derived from
  ``complete`` events as ``(latency_s - ttft_s) / (tokens - 1)``.
* ``reserve_wait`` — scalar q=0.95 of page-reservation wait seconds, added
  when the pool is short at admission time (mirrors the heuristic's
  pessimistic reserve term).

``predict_ttft`` returns ``None`` (never a guess) when the phases required
for the request's admission path are missing, which the scheduler treats as
"fall back to the histogram heuristic".
"""

import argparse
import json
import os
import sys

import numpy as np

FORMAT = "cloud_tpu.admission_model.v1"

#: Phase quantiles baked into the fit.  The median is the right operating
#: point for additive phase arithmetic (summing p95s compounds pessimism);
#: reserve_wait stays pessimistic because a short pool is already a tail
#: condition when it triggers.
_PHASE_Q = {"prefill": 0.5, "prefill_chunk": 0.5, "token": 0.5,
            "reserve_wait": 0.95}


def _quantile(values, q):
    return float(np.quantile(np.asarray(values, dtype=np.float64), q))


def _fit_binned_linear(samples, q):
    """Count-weighted LS line through per-bucket quantiles.

    ``samples`` is a list of ``(bucket, seconds)`` pairs.  Returns
    ``(intercept, slope, n)``.  With a single distinct bucket the slope is
    pinned to zero so the model extrapolates flat rather than wildly.
    """
    by_bucket = {}
    for bucket, dur in samples:
        by_bucket.setdefault(int(bucket), []).append(float(dur))
    buckets = sorted(by_bucket)
    qs = np.asarray([_quantile(by_bucket[b], q) for b in buckets])
    counts = np.asarray([len(by_bucket[b]) for b in buckets], dtype=np.float64)
    xs = np.asarray(buckets, dtype=np.float64)
    if len(buckets) == 1:
        return float(qs[0]), 0.0, len(samples)
    w = counts / counts.sum()
    xm = float((w * xs).sum())
    ym = float((w * qs).sum())
    var = float((w * (xs - xm) ** 2).sum())
    slope = float((w * (xs - xm) * (qs - ym)).sum() / var) if var > 0 else 0.0
    return ym - slope * xm, slope, len(samples)


class AdmissionModel:
    """A fitted per-phase TTFT model; see the module docstring for shape."""

    def __init__(self, doc):
        if not isinstance(doc, dict) or doc.get("format") != FORMAT:
            raise ValueError(
                "not a %s document (format=%r)" % (FORMAT, doc.get("format")
                                                   if isinstance(doc, dict)
                                                   else type(doc).__name__))
        self.doc = doc
        self.phases = doc["phases"]
        if not isinstance(self.phases, dict):
            raise ValueError("phases must be a mapping")
        for name, phase in self.phases.items():
            kind = phase.get("kind")
            if kind == "linear":
                [float(v) for v in phase["weights"]]
            elif kind == "quantile":
                float(phase["value"])
            else:
                raise ValueError("phase %r has unknown kind %r" % (name, kind))

    def _scalar(self, name):
        phase = self.phases.get(name)
        return None if phase is None else max(float(phase["value"]), 0.0)

    def _prefill_s(self, bucket):
        phase = self.phases.get("prefill")
        if phase is None:
            return None
        w0, w1 = (float(v) for v in phase["weights"])
        return max(w0 + w1 * float(bucket), 0.0)

    def predict_ttft(self, accrued, position, bucket, prompt_len, n_chunks,
                     pool_short):
        """Predicted TTFT in seconds, or None to fall back to the heuristic.

        Mirrors the scheduler's histogram arithmetic phase for phase:
        ``position`` requests drain ahead of this one, then its own prefill
        runs (``n_chunks`` chunk passes interleaved with decode ticks when
        chunked prefill is on, one dense pass otherwise).
        """
        del prompt_len  # the bucket is the cost-relevant resolution
        if n_chunks is not None:
            chunk_s = self._scalar("prefill_chunk")
            if chunk_s is None:
                return None
            token_s = self._scalar("token") or 0.0
            predicted = (accrued + position * chunk_s + n_chunks * chunk_s
                         + max(n_chunks - 1, 0) * token_s)
        else:
            prefill_s = self._prefill_s(bucket)
            if prefill_s is None:
                return None
            predicted = accrued + (position + 1) * prefill_s
        if pool_short:
            reserve_s = self._scalar("reserve_wait")
            if reserve_s is not None:
                predicted += reserve_s
        return float(predicted)


def load_model(path):
    """Load a fitted model; raises OSError/ValueError/KeyError on bad input."""
    with open(path, "r", encoding="utf-8") as handle:
        doc = json.load(handle)
    return AdmissionModel(doc)


def _iter_trace_files(paths):
    for path in paths:
        if os.path.isdir(path):
            names = sorted(name for name in os.listdir(path)
                           if name.endswith(".jsonl"))
            if not names:
                raise ValueError("no .jsonl trace files under %s" % path)
            for name in names:
                yield os.path.join(path, name)
        else:
            yield path


def _iter_events(files):
    for path in files:
        with open(path, "r", encoding="utf-8") as handle:
            for line in handle:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except ValueError:
                    continue  # torn tail line from a crashed writer
                if record.get("kind") != "reqtrace":
                    continue
                payload = record.get("payload")
                if isinstance(payload, dict):
                    yield payload


def fit(trace_paths):
    """Fit a model document from reqtrace JSONL files or directories."""
    files = list(_iter_trace_files(trace_paths))
    prefill, chunks, tokens, reserves = [], [], [], []
    n_events = 0
    for payload in _iter_events(files):
        n_events += 1
        event = payload.get("event")
        if event == "prefill" and "bucket" in payload and "dur_s" in payload:
            # Chunked prefills stamp a `chunks` count and their dur_s spans
            # the interleaved decode ticks — wrong cost basis for the dense
            # path, which is the only consumer of this phase.
            if "chunks" not in payload:
                prefill.append((payload["bucket"], payload["dur_s"]))
        elif event == "prefill_chunk" and "dur_s" in payload:
            chunks.append(float(payload["dur_s"]))
        elif event == "complete":
            latency = payload.get("latency_s")
            ttft = payload.get("ttft_s")
            n_tokens = payload.get("tokens", 0)
            if latency is not None and ttft is not None and n_tokens > 1:
                tokens.append(max(latency - ttft, 0.0) / (n_tokens - 1))
        elif event == "pages_reserved" and "wait_s" in payload:
            reserves.append(float(payload["wait_s"]))
    if n_events == 0:
        raise ValueError("no reqtrace events in %s" % (trace_paths,))
    phases = {}
    if prefill:
        w0, w1, n = _fit_binned_linear(prefill, _PHASE_Q["prefill"])
        phases["prefill"] = {"kind": "linear", "q": _PHASE_Q["prefill"],
                             "features": ["const", "bucket"],
                             "weights": [w0, w1], "n": n}
    for name, values in (("prefill_chunk", chunks), ("token", tokens),
                         ("reserve_wait", reserves)):
        if values:
            phases[name] = {"kind": "quantile", "q": _PHASE_Q[name],
                            "value": _quantile(values, _PHASE_Q[name]),
                            "n": len(values)}
    return {"format": FORMAT,
            "fit": {"events": n_events, "files": [os.path.basename(f)
                                                  for f in files],
                    "requests": len(tokens)},
            "phases": phases}


def _cmd_fit(args):
    doc = fit(args.trace)
    with open(args.out, "w", encoding="utf-8") as handle:
        json.dump(doc, handle, indent=2, sort_keys=True)
        handle.write("\n")
    if not args.quiet:
        print("wrote %s: %d events -> phases %s"
              % (args.out, doc["fit"]["events"], sorted(doc["phases"])))
    return 0


def _cmd_show(args):
    model = load_model(args.model)
    print(json.dumps(model.doc, indent=2, sort_keys=True))
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m cloud_tpu.serving.admission",
        description="Fit/inspect the reqtrace-derived TTFT admission model.")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_fit = sub.add_parser("fit", help="fit a model from reqtrace JSONL")
    p_fit.add_argument("--trace", nargs="+", required=True,
                       help="reqtrace .jsonl files or directories holding them")
    p_fit.add_argument("--out", default="admission_model.json")
    p_fit.add_argument("--quiet", action="store_true")
    p_fit.set_defaults(func=_cmd_fit)
    p_show = sub.add_parser("show", help="print a fitted model")
    p_show.add_argument("--model", required=True)
    p_show.set_defaults(func=_cmd_show)
    args = parser.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
