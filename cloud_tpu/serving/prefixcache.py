"""graftshare: host-side radix index over token prefixes of KV pages.

Production decode traffic shares long prompt prefixes (system prompts,
few-shot templates, multi-turn history). Because the paged pool gives
every `page_size`-token run of KV cache a physical identity (kvpool),
and because serve prefill writes prompts in CANONICAL layout (token i of
the prompt at cache slot i — engine.py), two requests whose prompts
agree on their first `k * page_size` tokens produce bitwise-identical
content in their first k pages. This module indexes those pages by the
token runs that produced them, SGLang/RadixAttention-style, at page
granularity: a trie whose edges are `page_size`-token tuples and whose
nodes carry the physical page holding that run's KV.

At admission the scheduler consults `match(prompt)`: matched pages map
straight into the new request's page table (pool refcount shared, pages
never copied) and prefill starts at the divergence point — TTFT drops
from O(prompt) to O(suffix). A divergence INSIDE a page yields a
partial match: the matched page becomes a read-only copy-on-write
source whose leading tokens are reconstructed into a fresh page by the
insert scatter (the trie page itself is never written).

The trie holds one pool reference per indexed page, bounded by
`max_pages` (the configurable HBM budget). Eviction is LRU over leaf
nodes whose page has no other holder (pool refcount 1 = trie only);
pages referenced by in-flight requests are never evicted.

Only FULL prompt pages strictly before the last prompt token are ever
registered: decode writes start at the first post-prompt slot, so
indexed pages are immutable for the request's lifetime, and a match is
capped at `len(prompt) - 1` tokens — at least one suffix token must
remain to prefill (the first sampled token comes from the last prompt
position).
"""

import dataclasses
import threading

from cloud_tpu.serving import reqtrace


@dataclasses.dataclass
class PrefixMatch:
    """A prefix-cache hit. The caller owns one pool reference on every
    page listed here (full pages and the partial CoW source) and must
    `pool.free` them when the request completes or the match is
    trimmed."""
    pages: list           # full shared pages, logical order
    prefix_len: int       # matched tokens: len(pages)*page_size + partial_len
    partial_page: object  # CoW source page id, or None
    partial_len: int      # matched tokens inside partial_page


class _Node:
    __slots__ = ("key", "page", "children", "parent", "stamp")

    def __init__(self, key, page, parent):
        self.key = key          # page_size-token tuple
        self.page = page        # physical page id (trie holds one ref)
        self.children = {}      # key tuple -> _Node
        self.parent = parent    # _Node or None (root child)
        self.stamp = 0          # LRU clock at last touch


class PrefixCache:
    """Page-granular radix index with LRU eviction under a page budget.

    Thread-safe. Lock order is trie -> pool (the pool never calls back
    into the trie). `probe` is side-effect-free (window ordering);
    `match` takes pool references on the returned pages so concurrent
    eviction can never recycle a page an admitted request is mapping.
    """

    def __init__(self, pool, max_pages=None):
        self.pool = pool
        self.page_size = pool.page_size
        if max_pages is None:
            max_pages = max(pool.capacity // 2, 1)
        self.max_pages = int(max_pages)
        self._lock = threading.Lock()
        self._root = {}    # key tuple -> _Node
        self._nodes = 0
        self._pages_held = 0
        self._clock = 0
        self._lookups = 0
        self._hits = 0
        self._partial_hits = 0
        self._evictions = 0
        self._matched_tokens = 0

    # -- lookup -------------------------------------------------------

    def _walk(self, tokens):
        """Longest full-page descent for `tokens`, capped so at least
        one token remains unmatched. Returns (nodes, limit)."""
        limit = len(tokens) - 1  # >=1 suffix token must survive
        page = self.page_size
        nodes = []
        children = self._root
        while (len(nodes) + 1) * page <= limit:
            key = tuple(tokens[len(nodes) * page:(len(nodes) + 1) * page])
            node = children.get(key)
            if node is None:
                break
            nodes.append(node)
            children = node.children
        return nodes, limit

    def _partial(self, nodes, tokens, limit):
        """Best partial-page continuation below the deepest full match:
        the child sharing the longest nonzero leading token run with the
        remaining prompt."""
        children = nodes[-1].children if nodes else self._root
        start = len(nodes) * self.page_size
        rest = tuple(tokens[start:limit])
        best, best_len = None, 0
        for key, node in children.items():
            run = 0
            for a, b in zip(key, rest):
                if a != b:
                    break
                run += 1
            if run > best_len:
                best, best_len = node, run
        return best, best_len

    def probe(self, tokens):
        """Matched-token count for `tokens` with NO side effects — the
        admission window sorts by this (longest radix match first)."""
        with self._lock:
            nodes, limit = self._walk(tokens)
            _, part_len = self._partial(nodes, tokens, limit)
            return len(nodes) * self.page_size + part_len

    def match(self, tokens):
        """Longest indexed prefix of `tokens`, with pool references
        taken on every returned page. Returns a PrefixMatch (empty on
        miss: prefix_len 0)."""
        with self._lock:
            self._lookups += 1
            nodes, limit = self._walk(tokens)
            part, part_len = self._partial(nodes, tokens, limit)
            self._clock += 1
            for node in nodes:
                node.stamp = self._clock
            if part is not None and part_len > 0:
                part.stamp = self._clock
                self._partial_hits += 1
            pages = [node.page for node in nodes]
            prefix_len = len(pages) * self.page_size + part_len
            if prefix_len:
                self._hits += 1
                self._matched_tokens += prefix_len
            held = pages + ([part.page] if part_len else [])
            if held:
                self.pool.share(held)
            return PrefixMatch(
                pages=pages, prefix_len=prefix_len,
                partial_page=part.page if part_len else None,
                partial_len=part_len)

    # -- registration -------------------------------------------------

    def register(self, tokens, page_ids):
        """Indexes the full prompt pages of a freshly-inserted request:
        `page_ids[i]` holds tokens `[i*page_size, (i+1)*page_size)` in
        canonical layout. Only pages strictly before the last prompt
        token are registered (decode never writes them). Existing nodes
        keep their page (first writer wins — identical content); new
        nodes take a pool reference, evicting LRU entries to stay under
        the budget. Registration quietly stops early when the budget
        cannot be met."""
        page = self.page_size
        n_full = min((len(tokens) - 1) // page, len(page_ids))
        if n_full <= 0:
            return 0
        with self._lock:
            self._clock += 1
            children = self._root
            parent = None
            registered = 0
            for i in range(n_full):
                key = tuple(tokens[i * page:(i + 1) * page])
                node = children.get(key)
                if node is None:
                    if (self._pages_held + 1 > self.max_pages
                            and not self._evict_locked(1)):
                        break
                    node = _Node(key, int(page_ids[i]), parent)
                    self.pool.share([node.page])
                    children[key] = node
                    self._nodes += 1
                    self._pages_held += 1
                    registered += 1
                node.stamp = self._clock
                parent = node
                children = node.children
            return registered

    # -- eviction -----------------------------------------------------

    def _iter_nodes(self):
        stack = list(self._root.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    def _evict_locked(self, need):
        """Drops up to `need` LRU leaf pages with no outside holder.
        Returns pages actually freed."""
        freed = 0
        while freed < need:
            victims = [n for n in self._iter_nodes()
                       if not n.children
                       and self.pool.refcount(n.page) == 1]
            if not victims:
                break
            victim = min(victims, key=lambda n: n.stamp)
            self._unlink_locked(victim)
            freed += 1
        return freed

    def _unlink_locked(self, node):
        siblings = (node.parent.children if node.parent is not None
                    else self._root)
        del siblings[node.key]
        self._nodes -= 1
        self._pages_held -= 1
        self._evictions += 1
        self.pool.free([node.page])

    def evict(self, n_pages):
        """Best-effort LRU eviction of `n_pages` (reclaim pressure from
        a blocked reservation). Returns pages freed."""
        with self._lock:
            freed = self._evict_locked(int(n_pages))
        if freed:
            tracer = reqtrace.get()
            if tracer is not None:
                # Global lane (rid=None): cache-pressure evictions are
                # not owned by any one request but explain why the
                # requests around them waited for pages.
                tracer.emit(None, "prefix_evict", pages=freed,
                            requested=int(n_pages))
        return freed

    def clear(self):
        """Releases every indexed page (pool refs included). Pages
        still mapped by in-flight requests survive via their own refs."""
        with self._lock:
            pages = [n.page for n in self._iter_nodes()]
            if pages:
                self.pool.free(pages)
            self._root = {}
            self._nodes = 0
            self._pages_held = 0

    def held_pages(self):
        """Pages the trie currently holds a reference on."""
        with self._lock:
            return sorted(n.page for n in self._iter_nodes())

    # -- accounting ---------------------------------------------------

    def reset_stats(self):
        with self._lock:
            self._lookups = self._hits = self._partial_hits = 0
            self._matched_tokens = 0
            self._evictions = 0

    def stats(self):
        with self._lock:
            return {
                "nodes": self._nodes,
                "pages_held": self._pages_held,
                "max_pages": self.max_pages,
                "lookups": self._lookups,
                "hits": self._hits,
                "partial_hits": self._partial_hits,
                "hit_rate": (self._hits / self._lookups
                             if self._lookups else 0.0),
                "matched_tokens": self._matched_tokens,
                "evictions": self._evictions,
            }


__all__ = ["PrefixCache", "PrefixMatch"]
