"""graftserve: continuous-batching decode over a paged KV-cache pool.

See cloud_tpu/serving/README.md for the architecture. Public surface:

- `PagePool` — host-side physical page accounting (kvpool.py)
- `DecodeEngine` — slot-indexed jitted tick/insert/evict (engine.py)
- `Scheduler`/`ServeRequest`/`ServeResult` — threads, admission,
  backpressure, telemetry (scheduler.py)
- `RequestTracer` — per-request lifecycle JSONL tracing behind
  `CLOUD_TPU_REQTRACE` (reqtrace.py)
- `LoadSpec` — open-arrival load generation (loadgen.py)
"""

from cloud_tpu.serving.engine import (DecodeEngine, PrefillResult,
                                      RetraceError)
from cloud_tpu.serving.kvpool import PagePool
from cloud_tpu.serving.loadgen import LoadSpec
from cloud_tpu.serving.reqtrace import RequestTracer
from cloud_tpu.serving.scheduler import (Scheduler, ServeRequest,
                                         ServeResult)

__all__ = [
    "DecodeEngine",
    "LoadSpec",
    "PagePool",
    "PrefillResult",
    "RequestTracer",
    "RetraceError",
    "Scheduler",
    "ServeRequest",
    "ServeResult",
]
