"""graftserve: continuous-batching decode over a paged KV-cache pool.

See cloud_tpu/serving/README.md for the architecture. Public surface:

- `PagePool` — host-side physical page accounting (kvpool.py)
- `DecodeEngine` — slot-indexed jitted tick/insert/evict (engine.py)
- `Scheduler`/`ServeRequest`/`ServeResult` — threads, admission,
  backpressure, telemetry (scheduler.py)
"""

from cloud_tpu.serving.engine import (DecodeEngine, PrefillResult,
                                      RetraceError)
from cloud_tpu.serving.kvpool import PagePool
from cloud_tpu.serving.scheduler import (Scheduler, ServeRequest,
                                         ServeResult)

__all__ = [
    "DecodeEngine",
    "PagePool",
    "PrefillResult",
    "RetraceError",
    "Scheduler",
    "ServeRequest",
    "ServeResult",
]
