"""graftserve: continuous-batching decode over a paged KV-cache pool.

See cloud_tpu/serving/README.md for the architecture. Public surface:

- `PagePool` — host-side physical page accounting (kvpool.py)
- `DecodeEngine` — slot-indexed jitted tick/insert/evict (engine.py)
- `Scheduler`/`ServeRequest`/`ServeResult` — threads, admission,
  backpressure, telemetry (scheduler.py)
- `RequestTracer` — per-request lifecycle JSONL tracing behind
  `CLOUD_TPU_REQTRACE` (reqtrace.py)
- `LoadSpec` — open-arrival load generation (loadgen.py)
- `ServeFault` taxonomy (`SlotHang`, `SlotEvicted`, `PrefillFailed`,
  `PoolSqueezed`, `ServeShed`) — typed serving faults for graftstorm
  chaos recovery and SLO-aware admission (faults.py)
"""

from cloud_tpu.serving.engine import (DecodeEngine, PrefillResult,
                                      RetraceError)
from cloud_tpu.serving.faults import (PoolSqueezed, PrefillFailed,
                                      ServeFault, ServeShed,
                                      SlotEvicted, SlotHang)
from cloud_tpu.serving.kvpool import PagePool
from cloud_tpu.serving.loadgen import LoadSpec
from cloud_tpu.serving.reqtrace import RequestTracer
from cloud_tpu.serving.scheduler import (Scheduler, ServeRequest,
                                         ServeResult)

__all__ = [
    "DecodeEngine",
    "LoadSpec",
    "PagePool",
    "PoolSqueezed",
    "PrefillFailed",
    "PrefillResult",
    "RequestTracer",
    "RetraceError",
    "Scheduler",
    "ServeFault",
    "ServeRequest",
    "ServeResult",
    "ServeShed",
    "SlotEvicted",
    "SlotHang",
]
