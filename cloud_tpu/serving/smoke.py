"""graftserve smoke: the continuous-vs-synchronous proof, CPU-sized.

`python -m cloud_tpu.serving.smoke` runs ≥8 concurrent mixed-length
requests through the scheduler and enforces the serving acceptance
contract end to end:

1. THROUGHPUT — aggregate tokens/sec must be >= MIN_SPEEDUP (2.0) times
   a batch-synchronous baseline: `generate()` over FCFS arrival-order
   batches at the SAME slot count, each batch running to its longest
   member's max_new_tokens (the hostage effect continuous batching
   exists to kill). Both sides are timed warm.
2. ZERO RETRACE — after `Scheduler.warmup()`, the whole serve pass must
   add zero traces and zero compiles (`runtime.compile_stats` delta;
   the engine's sentinel also runs in strict mode every tick).
3. BIT-IDENTICAL / NO LEAKAGE — every served request's tokens must
   equal its solo `generate()` decode exactly. Slots are reused across
   requests (more requests than slots), so equality is also the
   cross-request leakage check: a stale page or validity row would
   corrupt some continuation.

Writes `serving_smoke.json` (summary) next to the graftscope artifacts
(`telemetry.jsonl` etc.) in --out-dir; CI uploads the directory.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

MIN_SPEEDUP = 2.0


def build_model():
    """CPU-friendly but big enough that a decode tick is device-bound
    (the host round trip per tick must not dominate the comparison)."""
    import jax.numpy as jnp

    from cloud_tpu.models import TransformerLM
    return TransformerLM(vocab_size=1024, num_layers=6, num_heads=6,
                         d_model=384, d_ff=1536, max_seq_len=64,
                         compute_dtype=jnp.float32)


def build_requests(slots, waves=None):
    """Mixed-length arrival pattern, one long + (slots-1) shorts per
    wave: under FCFS batch-synchronous decode every batch is hostage to
    its long request; under continuous batching the shorts stream
    through the other slots."""
    from cloud_tpu.serving import ServeRequest

    if waves is None:
        # One long per slot: all longs decode concurrently, so the
        # serve makespan stays near ONE long (48 ticks) while the
        # baseline pays 48 steps per hostage batch.
        waves = slots
    rng = np.random.default_rng(42)
    requests = []
    for wave in range(waves):
        specs = [(int(rng.integers(9, 17)), 48)]
        specs += [(int(rng.integers(3, 9)), int(rng.integers(1, 4)))
                  for _ in range(slots - 1)]
        for plen, max_new in specs:
            requests.append(ServeRequest(
                prompt=rng.integers(1, 512, (plen,)).astype(
                    np.int32).tolist(),
                max_new_tokens=max_new, temperature=0.0,
                rng_seed=1000 + len(requests)))
    return requests


def solo_oracle(model, params, requests):
    """Per-request solo generate() — the bit-identical reference."""
    import jax
    import jax.numpy as jnp

    from cloud_tpu.models import generate
    outs = []
    for req in requests:
        toks = generate(model, params,
                        jnp.asarray(req.prompt, jnp.int32)[None],
                        req.max_new_tokens,
                        rng=jax.random.PRNGKey(req.rng_seed),
                        temperature=req.temperature, top_k=req.top_k,
                        top_p=req.top_p, eos_token=req.eos_token)
        outs.append(np.asarray(toks)[0])
    return outs


def run_baseline(model, params, requests, slots, timed):
    """Batch-synchronous decode: FCFS batches of `slots`, left-padded,
    each run for its longest member's max_new_tokens. Returns (useful
    tokens, seconds) — useful counts only each request's OWN budget."""
    import jax
    import jax.numpy as jnp

    from cloud_tpu.models import generate

    t0 = time.monotonic()
    useful = 0
    for lo in range(0, len(requests), slots):
        batch = requests[lo:lo + slots]
        width = max(len(r.prompt) for r in batch)
        tokens = np.zeros((len(batch), width), np.int32)
        mask = np.zeros((len(batch), width), bool)
        for row, req in enumerate(batch):
            tokens[row, width - len(req.prompt):] = req.prompt
            mask[row, width - len(req.prompt):] = True
        out = generate(model, params, jnp.asarray(tokens),
                       max(r.max_new_tokens for r in batch),
                       rng=jax.random.PRNGKey(0), temperature=0.0,
                       prompt_mask=jnp.asarray(mask))
        jax.block_until_ready(out)
        useful += sum(r.max_new_tokens for r in batch)
    elapsed = time.monotonic() - t0
    return (useful, elapsed) if timed else (useful, None)


def run_serve(scheduler, requests):
    t0 = time.monotonic()
    futures = [scheduler.submit(req, timeout=30) for req in requests]
    results = [f.result(timeout=600) for f in futures]
    elapsed = time.monotonic() - t0
    return results, sum(r.max_new_tokens for r in requests), elapsed


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out-dir", default=os.environ.get(
        "CLOUD_TPU_TELEMETRY_DIR", "serving-smoke-out"))
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--min-speedup", type=float, default=float(
        os.environ.get("CLOUD_TPU_SMOKE_MIN_SPEEDUP", MIN_SPEEDUP)))
    args = parser.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp

    from cloud_tpu.monitoring import telemetry, watch
    from cloud_tpu.parallel import runtime
    from cloud_tpu.serving import Scheduler

    model = build_model()
    requests = build_requests(args.slots)
    assert len(requests) >= 8, "smoke must run >= 8 concurrent requests"
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]

    print("[smoke] solo oracle ({} requests)".format(len(requests)))
    oracle = solo_oracle(model, params, requests)
    print("[smoke] batch-synchronous baseline (slots={})".format(
        args.slots))
    run_baseline(model, params, requests, args.slots, timed=False)
    base_tokens, base_secs = run_baseline(model, params, requests,
                                          args.slots, timed=True)

    telemetry.enable(args.out_dir)
    watch.install(stall_deadline=120.0, out_dir=args.out_dir)
    # Pool sized past slots*pages_per_slot: the extra pages let queued
    # requests hold reservations (prefill done, awaiting a slot) while
    # every slot is busy — admission overlaps the tick loop.
    pages_per_slot = model.max_seq_len // 16
    scheduler = Scheduler(model, params, slots=args.slots, page_size=16,
                          num_pages=(args.slots + 4) * pages_per_slot
                          + 1,
                          admission_window=len(requests),
                          strict_no_retrace=True).start()
    try:
        buckets = sorted({scheduler._bucket(r) for r in requests})
        print("[smoke] warmup over buckets {}".format(buckets))
        scheduler.warmup(buckets,
                         sampling_configs=[(("temperature", 0.0),)])
        warm = runtime.compile_stats()
        print("[smoke] serve pass")
        results, serve_tokens, serve_secs = run_serve(scheduler,
                                                      requests)
        after = runtime.compile_stats()
    finally:
        scheduler.close()
        watch.uninstall()

    mismatches = [i for i, (res, ref) in enumerate(zip(results, oracle))
                  if not np.array_equal(res.tokens, ref)]
    new_traces = after["n_traces"] - warm["n_traces"]
    new_compiles = after["n_compiles"] - warm["n_compiles"]
    base_tps = base_tokens / base_secs
    serve_tps = serve_tokens / serve_secs
    speedup = serve_tps / base_tps
    stats = scheduler.stats()

    summary = {
        "requests": len(requests),
        "slots": args.slots,
        "baseline_tokens_per_sec": base_tps,
        "serve_tokens_per_sec": serve_tps,
        "speedup": speedup,
        "min_speedup": args.min_speedup,
        "new_traces_post_warmup": new_traces,
        "new_compiles_post_warmup": new_compiles,
        "mismatched_requests": mismatches,
        "ttft_p50_s": stats["ttft"].get("p50"),
        "token_latency_p99_s": stats["token_latency"].get("p99"),
        "requests_per_sec": stats["requests_per_sec"],
    }
    os.makedirs(args.out_dir, exist_ok=True)
    with open(os.path.join(args.out_dir, "serving_smoke.json"),
              "w") as fh:
        json.dump(summary, fh, indent=2, sort_keys=True)
    tele = telemetry.get()
    if tele is not None:
        tele.flush(wait=True)
        telemetry.disable()

    print("[smoke] baseline {:.1f} tok/s | serve {:.1f} tok/s | "
          "speedup {:.2f}x (floor {:.1f}x)".format(
              base_tps, serve_tps, speedup, args.min_speedup))
    print("[smoke] post-warmup traces={} compiles={} | "
          "mismatches={}".format(new_traces, new_compiles,
                                 len(mismatches)))
    failures = []
    if speedup < args.min_speedup:
        failures.append("speedup {:.2f}x < {:.1f}x".format(
            speedup, args.min_speedup))
    if new_traces or new_compiles:
        failures.append("retrace after warmup ({} traces, {} "
                        "compiles)".format(new_traces, new_compiles))
    if mismatches:
        failures.append("requests {} diverged from solo generate() "
                        "(cross-request leakage or rng drift)".format(
                            mismatches))
    if failures:
        print("[smoke] FAIL: " + "; ".join(failures))
        return 1
    print("[smoke] PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
