"""graftserve smoke: the serving acceptance contracts, CPU-sized.

`python -m cloud_tpu.serving.smoke [--scenario
base|prefix|spec|chaos|all]` runs the continuous-batching scheduler
through four end-to-end scenarios, each enforcing its slice of the
serving contract:

base (ISSUE 10) — ≥8 concurrent mixed-length requests:
  1. THROUGHPUT — aggregate tokens/sec >= MIN_SPEEDUP (2.0) times a
     batch-synchronous baseline: `generate()` over FCFS arrival-order
     batches at the SAME slot count, each batch running to its longest
     member's max_new_tokens (the hostage effect continuous batching
     exists to kill). Both sides are timed warm.
  2. ZERO RETRACE — after `Scheduler.warmup()`, the whole serve pass
     must add zero traces and zero compiles (`runtime.compile_stats`
     delta; the engine's sentinel also runs strict every tick).
  3. BIT-IDENTICAL / NO LEAKAGE — every served request's tokens must
     equal its solo `generate()` decode exactly. Slots are reused
     across requests, so equality doubles as the cross-request leakage
     check.

prefix (ISSUE 11, graftshare) — a 90%-shared-prefix fleet served twice,
  prefix cache ON then OFF (same requests, same model):
  4. TTFT — the ON run's cache-hit TTFT p50 must be >= MIN_TTFT_RATIO
     (5.0) times better than the OFF run's TTFT p50: radix-matched
     pages map into the new request's page table and only the suffix
     prefills, so TTFT drops from O(prompt) to O(suffix).
  5. Zero post-warmup traces with the cache on (hit prefills reuse the
     miss executables), bit-identity regardless of sharing, and the
     drained-pool invariant: after the fleet completes, every held page
     is exactly one prefix-cache reference (refcount leak detector).

spec (ISSUE 11, speculative tick) — greedy fleet served twice, plain
  tick then speculative (draft model + verify inside the same tick):
  6. THROUGHPUT — tokens/sec with speculation >= MIN_SPEC_SPEEDUP (1.5)
     times the plain tick. The draft here shares the target's first
     block and head while the target's remaining blocks are exact
     zero-residual identities, so draft and target agree by
     construction (acceptance 1.0) — the gate measures the tick
     plumbing's ceiling, not draft quality.
  7. Bit-identity to solo generate() (the pinned accept/reject math),
     zero post-warmup traces, drained pool.

chaos (ISSUE 14, graftstorm) — a mixed greedy/top-p fleet served twice,
  clean then under injected serving faults (`prefill_fail`,
  `slot_hang`, `pool_squeeze` at exact post-warmup ticks):
  8. ZERO LOST — every offered request completes; a faulted slot is
     evicted mid-flight and its request re-prefills from retained
     progress, finishing BIT-IDENTICAL to solo generate() (the rng
     schedule is re-based, not restarted).
  9. Bounded blast radius — the chaos leg's token-latency p99 stays
     within CHAOS_P99_FACTOR of the clean leg's, zero post-warmup
     traces/compiles (recovery reuses warmed shapes), and the pool
     drains leak-free (the faulted slot's pages return exactly once).

chunked (ISSUE 16) — a heavy-prompt mix (>= 25% long prompts near the
  context limit, the rest short prompts with long decodes) served
  twice at the same offered load, chunked prefill ON then OFF:
  10. DECODE GAP — the unchunked leg's commit-to-commit decode-gap p99
      must be >= MIN_CHUNK_GAP_RATIO (3.0) times the chunked leg's: a
      monolithic long prefill monopolises the device between two
      decode commits, while the interleave bounds that window to one
      tick plus one chunk.
  11. Bit-identity to solo generate() on BOTH legs (chunk boundaries
      change executable shapes, never logits), zero post-warmup
      traces with chunking on (warmup drives the chunk + tail-bucket
      surface), chunk dispatches observed on the ON leg only, and the
      drained-pool invariant (prefill holds release exactly once).

kvq (ISSUE 17, graftpack) — the same greedy fleet served twice at an
  EQUAL HBM byte budget, fp KV pages then int8 KV pages (per-page
  per-head f32 scales):
  12. CAPACITY — the int8 pool must admit >= MIN_KVQ_CAPACITY_RATIO
      (2.0) times the fp pool's full-context sessions under the same
      byte budget (the ~4x page-size shrink minus the scale sidecars),
      with the pool's advertised page_bytes matching the analytic
      per-layer formula on both legs.
  13. PARITY — the int8 leg's greedy decodes are bit-identical to the
      fp leg's AND to solo generate() (the dequant contract:
      k = int8 * scale, both dots f32), zero post-warmup traces on
      either leg, and leak-free drain. Capacity that costs correctness
      is not capacity.

offload (ISSUE 17, graftpack) — multi-turn conversations served three
  ways: host tier ON under a page budget too small to keep device
  prefixes resident (turn-2 admission PROMOTES demoted pages back),
  an ample-budget device-cache-hit control, and the same small budget
  with the host tier OFF (turn-2 re-prefills from scratch):
  14. TTFT — turn-2 TTFT p50 with the host tier stays within
      MAX_OFFLOAD_HIT_FACTOR (1.5x) of the device-hit control and
      beats the re-prefill control by >= MIN_OFFLOAD_REPREFILL_RATIO
      (3.0x): an H2D page copy costs more than a device hit but far
      less than recomputing the prefix.
  15. Every turn-2 admission on the offload leg promotes (the demote
      -> evict -> promote cycle actually ran), turn outputs are
      bit-identical across all three legs, zero post-warmup traces,
      leak-free drain — and a corrupted host entry (stamped digest
      mismatch) is refused as a typed `host_tier_corrupt` fault that
      falls back to re-prefill with the result still exact.

autoscale (ISSUE 18, graftflex) — one diurnal (sinusoidal-ramp)
  open-arrival run served twice under the same TTFT SLO: a
  fixed-capacity replica pinned at the ladder's LOW rung, then an
  elastic replica autoscaling across the pow2 ladder (the fixed leg
  also feeds the reqtrace corpus an admission model is fit from;
  the elastic leg loads it):
  16. GOODPUT — the elastic leg's SLO goodput must be >=
      MIN_AUTOSCALE_GOODPUT (1.5x) the fixed leg's at equal worst-case
      TTFT p99 (worst per-segment p99 within AUTOSCALE_P99_FACTOR of
      the fixed leg's): the narrow replica sheds at the crest where
      the elastic one widens instead.
  17. The elastic leg fires >= 1 grow AND >= 1 shrink resize (the ramp
      actually drove the policy both directions), the fixed leg fires
      none, every completed request on BOTH legs is bit-identical to
      solo generate() (resizes migrate in-flight rng schedules/eos
      latches exactly), and zero post-warmup traces/compiles on either
      leg — the warmup ladder walk pre-warms every rung's tick/insert/
      evict and every adjacent resize pair.

Relative gating (ISSUE 16): every performance gate above is an A/B
ratio of two legs run back-to-back in the same process on the same
rig, so load noise hits both legs alike. Even so, CI containers
jitter — PR 12's pristine-seed control leg measured 1.47x against the
1.5x spec floor. The advertised floors therefore WARN when missed;
the hard failure fires only below HARD_GATE_FRACTION of the floor,
where the A/B direction itself is in doubt. Correctness gates
(bit-identity, zero-retrace, leak-free drain, zero lost) stay hard.

Each scenario writes `serving_smoke[_<name>].json` next to the
graftscope artifacts in --out-dir; CI uploads the directory.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

MIN_SPEEDUP = 2.0
MIN_TTFT_RATIO = 5.0
MIN_SPEC_SPEEDUP = 1.5
MIN_CHUNK_GAP_RATIO = 3.0
CHAOS_P99_FACTOR = 10.0
CHAOS_PLAN = "prefill_fail@2,slot_hang@5,pool_squeeze@9:8,slot_hang@14"
CHUNK_SIZE = 16
MIN_KVQ_CAPACITY_RATIO = 2.0
MAX_OFFLOAD_HIT_FACTOR = 1.5
MIN_OFFLOAD_REPREFILL_RATIO = 3.0
MIN_AUTOSCALE_GOODPUT = 1.5
AUTOSCALE_P99_FACTOR = 1.5
AUTOSCALE_RATE_HI = 28.0
AUTOSCALE_SLO_MULT = 5.0
# Below this fraction of an advertised floor a missed ratio is a hard
# failure (the A/B direction itself is in doubt); between the two it
# only warns. Override: CLOUD_TPU_SMOKE_HARD_FRACTION.
HARD_GATE_FRACTION = 0.6


def build_model(max_seq_len=64, num_layers=6, vocab_size=1024):
    """CPU-friendly but big enough that a decode tick is device-bound
    (the host round trip per tick must not dominate the comparison)."""
    import jax.numpy as jnp

    from cloud_tpu.models import TransformerLM
    return TransformerLM(vocab_size=vocab_size, num_layers=num_layers,
                         num_heads=6, d_model=384, d_ff=1536,
                         max_seq_len=max_seq_len,
                         compute_dtype=jnp.float32)


def build_requests(slots, waves=None, prefix_share=0.0, seed=42):
    """Mixed-length arrival pattern, one long + (slots-1) shorts per
    wave: under FCFS batch-synchronous decode every batch is hostage to
    its long request; under continuous batching the shorts stream
    through the other slots. `prefix_share` makes that fraction of the
    short requests share one 32-token prompt prefix (distinct tails) —
    the graftshare bench knob. Sharing shrinks the long request's
    continuation (48 → 24): the batch-synchronous baseline pads its
    batch prompt to the widest member (32 + tail), and padded prompt +
    the batch's max_new must still fit build_model's max_seq_len."""
    from cloud_tpu.serving import ServeRequest

    if waves is None:
        # One long per slot: all longs decode concurrently, so the
        # serve makespan stays near ONE long (48 ticks) while the
        # baseline pays 48 steps per hostage batch.
        waves = slots
    rng = np.random.default_rng(seed)
    shared = rng.integers(1, 512, (32,)).astype(np.int32).tolist()
    long_new = 48 if prefix_share <= 0.0 else 24
    requests = []
    for wave in range(waves):
        specs = [(int(rng.integers(9, 17)), long_new, False)]
        specs += [(int(rng.integers(3, 9)), int(rng.integers(1, 4)),
                   float(rng.random()) < prefix_share)
                  for _ in range(slots - 1)]
        for plen, max_new, share in specs:
            tail = rng.integers(1, 512, (plen,)).astype(np.int32).tolist()
            requests.append(ServeRequest(
                prompt=(shared + tail) if share else tail,
                max_new_tokens=max_new, temperature=0.0,
                rng_seed=1000 + len(requests)))
    return requests


def build_prefix_requests(model, n_requests=20, share=0.9,
                          suffix_lo=2, suffix_hi=4, max_new=2,
                          seed=7):
    """`share` of the fleet extends one long common prefix (distinct
    short tails); the rest are fully distinct long prompts. The prefix
    fills all but one page-and-change of the context so a cache hit
    prefills ~suffix tokens instead of ~prefix_len."""
    from cloud_tpu.serving import ServeRequest

    rng = np.random.default_rng(seed)
    prefix_len = model.max_seq_len - 16
    roots = [rng.integers(1, 512, (prefix_len,)).astype(np.int32).tolist()
             for _ in range(2)]
    requests = []
    for i in range(n_requests):
        root = roots[0] if (i % n_requests) < share * n_requests \
            else roots[1]
        tail = rng.integers(1, 512, (int(rng.integers(
            suffix_lo, suffix_hi + 1)),)).astype(np.int32).tolist()
        requests.append(ServeRequest(
            prompt=root + tail, max_new_tokens=max_new,
            temperature=0.0, rng_seed=2000 + i))
    return requests


def split_draft(params, draft_layers=1):
    """Makes (target_params, draft_params) that agree by construction:
    the draft keeps the first `draft_layers` blocks + embeddings + head
    verbatim, and every later target block is forced to an exact
    identity (zero attention-out and mlp-out projections → pre-norm
    residual adds exact 0.0). Target and draft logits are then equal,
    so greedy speculation accepts every proposal — the smoke measures
    the tick's speculative plumbing at its acceptance ceiling."""
    import jax.numpy as jnp

    def _zeroed(tree):
        return {k: jnp.zeros_like(v) if not isinstance(v, dict)
                else _zeroed(v) for k, v in tree.items()}

    target = dict(params)
    draft = {}
    n_blocks = sum(1 for k in params if k.startswith("block_"))
    for name, sub in params.items():
        if not name.startswith("block_"):
            draft[name] = sub
            continue
        idx = int(name.split("_")[1])
        if idx < draft_layers:
            draft[name] = sub
        else:
            blk = dict(sub)
            blk["attention"] = dict(blk["attention"],
                                    out=_zeroed(sub["attention"]["out"]))
            blk["mlp_out"] = _zeroed(sub["mlp_out"])
            target[name] = blk
    assert n_blocks > draft_layers
    return target, draft


def solo_oracle(model, params, requests):
    """Per-request solo generate() — the bit-identical reference."""
    import jax
    import jax.numpy as jnp

    from cloud_tpu.models import generate
    outs = []
    for req in requests:
        toks = generate(model, params,
                        jnp.asarray(req.prompt, jnp.int32)[None],
                        req.max_new_tokens,
                        rng=jax.random.PRNGKey(req.rng_seed),
                        temperature=req.temperature, top_k=req.top_k,
                        top_p=req.top_p, eos_token=req.eos_token)
        outs.append(np.asarray(toks)[0])
    return outs


def run_baseline(model, params, requests, slots, timed):
    """Batch-synchronous decode: FCFS batches of `slots`, left-padded,
    each run for its longest member's max_new_tokens. Returns (useful
    tokens, seconds) — useful counts only each request's OWN budget."""
    import jax
    import jax.numpy as jnp

    from cloud_tpu.models import generate

    t0 = time.monotonic()
    useful = 0
    for lo in range(0, len(requests), slots):
        batch = requests[lo:lo + slots]
        width = max(len(r.prompt) for r in batch)
        tokens = np.zeros((len(batch), width), np.int32)
        mask = np.zeros((len(batch), width), bool)
        for row, req in enumerate(batch):
            tokens[row, width - len(req.prompt):] = req.prompt
            mask[row, width - len(req.prompt):] = True
        out = generate(model, params, jnp.asarray(tokens),
                       max(r.max_new_tokens for r in batch),
                       rng=jax.random.PRNGKey(0), temperature=0.0,
                       prompt_mask=jnp.asarray(mask))
        jax.block_until_ready(out)
        useful += sum(r.max_new_tokens for r in batch)
    elapsed = time.monotonic() - t0
    return (useful, elapsed) if timed else (useful, None)


def run_serve(scheduler, requests):
    t0 = time.monotonic()
    futures = [scheduler.submit(req, timeout=30) for req in requests]
    results = [f.result(timeout=600) for f in futures]
    elapsed = time.monotonic() - t0
    return results, sum(r.max_new_tokens for r in requests), elapsed


def _write_summary(out_dir, name, summary):
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, name), "w") as fh:
        json.dump(summary, fh, indent=2, sort_keys=True)


def _gate_ratio(failures, warnings, label, ratio, floor):
    """Two-tier relative gate: both legs of `ratio` ran back-to-back
    on the same rig, so the comparison is load-robust — but CI
    containers still jitter enough to graze a fixed floor (PR 12:
    1.47x against 1.5x on a pristine seed). Missing the advertised
    floor warns; only falling below HARD_GATE_FRACTION of it fails."""
    fraction = float(os.environ.get("CLOUD_TPU_SMOKE_HARD_FRACTION",
                                    HARD_GATE_FRACTION))
    hard = floor * fraction
    if ratio < hard:
        failures.append(
            "{} {:.2f}x < hard floor {:.2f}x ({:.0f}% of the "
            "advertised {:.1f}x)".format(label, ratio, hard,
                                         100 * fraction, floor))
    elif ratio < floor:
        warnings.append(
            "{} {:.2f}x < advertised floor {:.1f}x (same-rig A/B "
            "direction holds; floor is advisory)".format(label, ratio,
                                                         floor))


def _check(failures, tag, warnings=None):
    for warning in warnings or ():
        print("[smoke:{}] WARN: {}".format(tag, warning))
    if failures:
        print("[smoke:{}] FAIL: {}".format(tag, "; ".join(failures)))
        return 1
    print("[smoke:{}] PASS".format(tag))
    return 0


def run_base(args):
    import jax
    import jax.numpy as jnp

    from cloud_tpu.monitoring import telemetry, watch
    from cloud_tpu.parallel import runtime
    from cloud_tpu.serving import Scheduler

    model = build_model()
    requests = build_requests(args.slots)
    assert len(requests) >= 8, "smoke must run >= 8 concurrent requests"
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]

    print("[smoke:base] solo oracle ({} requests)".format(len(requests)))
    oracle = solo_oracle(model, params, requests)
    print("[smoke:base] batch-synchronous baseline (slots={})".format(
        args.slots))
    run_baseline(model, params, requests, args.slots, timed=False)
    base_tokens, base_secs = run_baseline(model, params, requests,
                                          args.slots, timed=True)

    telemetry.enable(args.out_dir)
    watch.install(stall_deadline=120.0, out_dir=args.out_dir)
    # Pool sized past slots*pages_per_slot: the extra pages let queued
    # requests hold reservations (prefill done, awaiting a slot) while
    # every slot is busy — admission overlaps the tick loop.
    pages_per_slot = model.max_seq_len // 16
    scheduler = Scheduler(model, params, slots=args.slots, page_size=16,
                          num_pages=(args.slots + 4) * pages_per_slot
                          + 1,
                          admission_window=len(requests),
                          strict_no_retrace=True).start()
    try:
        buckets = sorted({scheduler._bucket(r) for r in requests})
        print("[smoke:base] warmup over buckets {}".format(buckets))
        scheduler.warmup(buckets,
                         sampling_configs=[(("temperature", 0.0),)])
        warm = runtime.compile_stats()
        print("[smoke:base] serve pass")
        results, serve_tokens, serve_secs = run_serve(scheduler,
                                                      requests)
        after = runtime.compile_stats()
        stats = scheduler.stats()
    finally:
        scheduler.close()
        watch.uninstall()

    mismatches = [i for i, (res, ref) in enumerate(zip(results, oracle))
                  if not np.array_equal(res.tokens, ref)]
    new_traces = after["n_traces"] - warm["n_traces"]
    new_compiles = after["n_compiles"] - warm["n_compiles"]
    base_tps = base_tokens / base_secs
    serve_tps = serve_tokens / serve_secs
    speedup = serve_tps / base_tps

    summary = {
        "requests": len(requests),
        "slots": args.slots,
        "baseline_tokens_per_sec": base_tps,
        "serve_tokens_per_sec": serve_tps,
        "speedup": speedup,
        "min_speedup": args.min_speedup,
        "new_traces_post_warmup": new_traces,
        "new_compiles_post_warmup": new_compiles,
        "mismatched_requests": mismatches,
        "ttft_p50_s": stats["ttft"].get("p50"),
        "token_latency_p99_s": stats["token_latency"].get("p99"),
        "requests_per_sec": stats["requests_per_sec"],
        "prefix_hit_rate": stats["prefix_hit_rate"],
    }
    _write_summary(args.out_dir, "serving_smoke.json", summary)
    tele = telemetry.get()
    if tele is not None:
        tele.flush(wait=True)
        telemetry.disable()

    print("[smoke:base] baseline {:.1f} tok/s | serve {:.1f} tok/s | "
          "speedup {:.2f}x (floor {:.1f}x)".format(
              base_tps, serve_tps, speedup, args.min_speedup))
    print("[smoke:base] post-warmup traces={} compiles={} | "
          "mismatches={}".format(new_traces, new_compiles,
                                 len(mismatches)))
    failures, warnings = [], []
    _gate_ratio(failures, warnings, "speedup", speedup,
                args.min_speedup)
    if new_traces or new_compiles:
        failures.append("retrace after warmup ({} traces, {} "
                        "compiles)".format(new_traces, new_compiles))
    if mismatches:
        failures.append("requests {} diverged from solo generate() "
                        "(cross-request leakage or rng drift)".format(
                            mismatches))
    return _check(failures, "base", warnings)


def run_prefix(args):
    import jax
    import jax.numpy as jnp

    from cloud_tpu.parallel import runtime
    from cloud_tpu.serving import Scheduler

    model = build_model(max_seq_len=256)
    requests = build_prefix_requests(model)
    n_shared = sum(1 for r in requests
                   if r.prompt[:16] == requests[0].prompt[:16])
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    print("[smoke:prefix] solo oracle ({} requests, {} share a "
          "prefix)".format(len(requests), n_shared))
    oracle = solo_oracle(model, params, requests)

    def _serve(prefix_cache):
        scheduler = Scheduler(model, params, slots=2, page_size=16,
                              admission_window=4,
                              strict_no_retrace=True,
                              prefix_cache=prefix_cache).start()
        try:
            buckets = sorted({scheduler._bucket(r) for r in requests})
            scheduler.warmup(buckets,
                             sampling_configs=[(("temperature", 0.0),)])
            warm = runtime.compile_stats()
            # Sequential submits: each TTFT is pure admission+prefill,
            # not queue wait — the honest O(prompt) vs O(suffix) read.
            results = [scheduler.submit(r, timeout=30).result(
                timeout=600) for r in requests]
            after = runtime.compile_stats()
            stats = scheduler.stats()
            time.sleep(0.3)
            if prefix_cache:
                scheduler.assert_drained()          # trie refs only
                scheduler.assert_drained(clear_prefix=True)
            leaked = scheduler.pool.leak_report()
            return results, stats, leaked, (
                after["n_traces"] - warm["n_traces"],
                after["n_compiles"] - warm["n_compiles"])
        finally:
            scheduler.close()

    print("[smoke:prefix] serve pass (prefix cache ON)")
    on_results, on_stats, on_leaked, on_traces = _serve(True)
    print("[smoke:prefix] serve pass (prefix cache OFF)")
    off_results, off_stats, _, _ = _serve(False)

    mism_on = [i for i, (res, ref) in enumerate(zip(on_results, oracle))
               if not np.array_equal(res.tokens, ref)]
    mism_off = [i for i, (res, ref) in enumerate(zip(off_results,
                                                     oracle))
                if not np.array_equal(res.tokens, ref)]
    hit_p50 = on_stats["ttft_hit"].get("p50")
    off_p50 = off_stats["ttft"].get("p50")
    ratio = (off_p50 / hit_p50) if hit_p50 else 0.0

    summary = {
        "requests": len(requests),
        "shared_fraction": n_shared / len(requests),
        "prefix_hits": on_stats["prefix_hits"],
        "prefix_hit_rate": on_stats["prefix_hit_rate"],
        "prefix_tokens_served": on_stats["prefix_tokens_served"],
        "cow_copies": on_stats["pool"]["cow_copies"],
        "ttft_hit_p50_s": hit_p50,
        "ttft_miss_p50_s": on_stats["ttft_miss"].get("p50"),
        "ttft_off_p50_s": off_p50,
        "ttft_ratio": ratio,
        "min_ttft_ratio": args.min_ttft_ratio,
        "new_traces_post_warmup": on_traces[0],
        "new_compiles_post_warmup": on_traces[1],
        "mismatched_on": mism_on,
        "mismatched_off": mism_off,
        "leaked_pages": on_leaked,
    }
    _write_summary(args.out_dir, "serving_smoke_prefix.json", summary)

    print("[smoke:prefix] TTFT p50 off {:.4f}s | hit {:.4f}s | ratio "
          "{:.1f}x (floor {:.1f}x) | hits {}/{}".format(
              off_p50 or -1, hit_p50 or -1, ratio, args.min_ttft_ratio,
              on_stats["prefix_hits"], len(requests)))
    failures, warnings = [], []
    _gate_ratio(failures, warnings, "TTFT ratio", ratio,
                args.min_ttft_ratio)
    if on_stats["prefix_hits"] < n_shared - 1:
        failures.append("only {} cache hits (expected >= {})".format(
            on_stats["prefix_hits"], n_shared - 1))
    if on_traces[0] or on_traces[1]:
        failures.append("retrace after warmup with prefix cache on "
                        "({} traces, {} compiles)".format(*on_traces))
    if mism_on or mism_off:
        failures.append("diverged from solo generate(): on={} off={}"
                        .format(mism_on, mism_off))
    if on_leaked:
        failures.append("page refcount leak after drain: {}".format(
            on_leaked))
    return _check(failures, "prefix", warnings)


def run_spec(args):
    import jax
    import jax.numpy as jnp

    from cloud_tpu.parallel import runtime
    from cloud_tpu.serving import Scheduler

    model = build_model()
    draft_model = build_model(num_layers=1)
    base_params = model.init(jax.random.PRNGKey(1),
                             jnp.zeros((1, 8), jnp.int32))["params"]
    params, draft_params = split_draft(base_params, draft_layers=1)

    rng = np.random.default_rng(3)
    from cloud_tpu.serving import ServeRequest
    requests = [ServeRequest(
        prompt=rng.integers(1, 512, (int(rng.integers(6, 13)),))
        .astype(np.int32).tolist(),
        max_new_tokens=40, temperature=0.0, rng_seed=3000 + i)
        for i in range(12)]

    print("[smoke:spec] solo oracle ({} requests)".format(len(requests)))
    oracle = solo_oracle(model, params, requests)

    def _serve(spec_k):
        kwargs = {}
        if spec_k:
            kwargs = dict(draft_model=draft_model,
                          draft_params=draft_params, spec_k=spec_k)
        scheduler = Scheduler(model, params, slots=4, page_size=16,
                              admission_window=len(requests),
                              strict_no_retrace=True, **kwargs).start()
        try:
            buckets = sorted({scheduler._bucket(r) for r in requests})
            scheduler.warmup(buckets,
                             sampling_configs=[(("temperature", 0.0),)])
            warm = runtime.compile_stats()
            results, tokens, secs = run_serve(scheduler, requests)
            after = runtime.compile_stats()
            stats = scheduler.stats()
            time.sleep(0.3)
            scheduler.assert_drained(clear_prefix=True)
            return results, tokens / secs, stats, (
                after["n_traces"] - warm["n_traces"],
                after["n_compiles"] - warm["n_compiles"])
        finally:
            scheduler.close()

    print("[smoke:spec] serve pass (plain tick)")
    plain_results, plain_tps, _, _ = _serve(0)
    print("[smoke:spec] serve pass (speculative, k={})".format(
        args.spec_k))
    spec_results, spec_tps, spec_stats, spec_traces = _serve(
        args.spec_k)

    mism = [i for i, (res, ref) in enumerate(zip(spec_results, oracle))
            if not np.array_equal(res.tokens, ref)]
    mism_plain = [i for i, (res, ref) in
                  enumerate(zip(plain_results, oracle))
                  if not np.array_equal(res.tokens, ref)]
    speedup = spec_tps / plain_tps

    summary = {
        "requests": len(requests),
        "spec_k": args.spec_k,
        "plain_tokens_per_sec": plain_tps,
        "spec_tokens_per_sec": spec_tps,
        "speedup": speedup,
        "min_speedup": args.min_spec_speedup,
        "spec_accept_rate": spec_stats["spec_accept_rate"],
        "new_traces_post_warmup": spec_traces[0],
        "new_compiles_post_warmup": spec_traces[1],
        "mismatched_spec": mism,
        "mismatched_plain": mism_plain,
    }
    _write_summary(args.out_dir, "serving_smoke_spec.json", summary)

    print("[smoke:spec] plain {:.1f} tok/s | spec {:.1f} tok/s | "
          "speedup {:.2f}x (floor {:.1f}x) | accept {:.2f}".format(
              plain_tps, spec_tps, speedup, args.min_spec_speedup,
              spec_stats["spec_accept_rate"]))
    failures, warnings = [], []
    _gate_ratio(failures, warnings, "spec speedup", speedup,
                args.min_spec_speedup)
    if spec_stats["spec_accept_rate"] < 0.9:
        failures.append(
            "accept rate {:.2f} < 0.9 with an agree-by-construction "
            "draft (verify math drifted)".format(
                spec_stats["spec_accept_rate"]))
    if spec_traces[0] or spec_traces[1]:
        failures.append("retrace after warmup with speculation on "
                        "({} traces, {} compiles)".format(*spec_traces))
    if mism or mism_plain:
        failures.append("diverged from solo generate(): spec={} "
                        "plain={}".format(mism, mism_plain))
    return _check(failures, "spec", warnings)


def build_chaos_requests(n_requests=12, seed=5):
    """Mixed greedy/top-p fleet for the chaos leg. Every third request
    samples (temperature + nucleus), so a requeue must re-base the rng
    schedule — greedy alone would pass trivially."""
    from cloud_tpu.serving import ServeRequest

    rng = np.random.default_rng(seed)
    requests = []
    for i in range(n_requests):
        plen = int(rng.integers(6, 17))
        prompt = rng.integers(1, 512, (plen,)).astype(np.int32).tolist()
        if i % 3 == 2:
            requests.append(ServeRequest(
                prompt=prompt, max_new_tokens=int(rng.integers(8, 15)),
                temperature=0.8, top_p=0.9, rng_seed=4000 + i))
        else:
            requests.append(ServeRequest(
                prompt=prompt, max_new_tokens=int(rng.integers(8, 21)),
                temperature=0.0, rng_seed=4000 + i))
    return requests


def run_chaos(args):
    import jax
    import jax.numpy as jnp

    from cloud_tpu.analysis import chaos
    from cloud_tpu.models.decoding import bucket_length
    from cloud_tpu.parallel import runtime
    from cloud_tpu.serving import Scheduler

    model = build_model()
    requests = build_chaos_requests()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    print("[smoke:chaos] solo oracle ({} requests)".format(len(requests)))
    oracle = solo_oracle(model, params, requests)

    def _serve(plan):
        slots = 4
        pages_per_slot = model.max_seq_len // 16
        scheduler = Scheduler(model, params, slots=slots, page_size=16,
                              num_pages=(slots + 3) * pages_per_slot + 1,
                              admission_window=len(requests),
                              strict_no_retrace=True).start()
        try:
            # A requeued request re-prefills its prompt + tokens-so-far,
            # which can land in a LARGER bucket than any original
            # prompt — warm those continuation buckets too or the
            # recovery path itself would retrace.
            buckets = {scheduler._bucket(r) for r in requests}
            buckets |= {bucket_length(
                len(r.prompt) + r.max_new_tokens - 1,
                model.max_seq_len) for r in requests}
            scheduler.warmup(sorted(buckets), sampling_configs=[
                (("temperature", 0.0),),
                (("temperature", 0.8), ("top_p", 0.9)),
            ])
            warm = runtime.compile_stats()
            if plan:
                chaos.install(plan)
            results, errors = [], []
            futures = [scheduler.submit(r, timeout=30) for r in requests]
            for i, future in enumerate(futures):
                try:
                    results.append(future.result(timeout=600))
                except BaseException as exc:  # noqa: BLE001
                    results.append(None)
                    errors.append("request {}: {}: {}".format(
                        i, type(exc).__name__, str(exc)[:120]))
            after = runtime.compile_stats()
            stats = scheduler.stats()
            time.sleep(0.3)
            scheduler.assert_drained(clear_prefix=True)
            leaked = scheduler.pool.leak_report()
            return results, errors, stats, leaked, (
                after["n_traces"] - warm["n_traces"],
                after["n_compiles"] - warm["n_compiles"])
        finally:
            chaos.uninstall()
            scheduler.close()

    print("[smoke:chaos] serve pass (clean control)")
    _, clean_errs, clean_stats, _, _ = _serve(None)
    print("[smoke:chaos] serve pass (chaos: {})".format(args.chaos_plan))
    results, errors, stats, leaked, traces = _serve(args.chaos_plan)

    mismatches = [i for i, (res, ref) in enumerate(zip(results, oracle))
                  if res is None or not np.array_equal(res.tokens, ref)]
    clean_p99 = clean_stats["token_latency"].get("p99") or 0.0
    chaos_p99 = stats["token_latency"].get("p99") or 0.0
    p99_bound = max(args.chaos_p99_factor * clean_p99, 0.5)

    summary = {
        "requests": len(requests),
        "chaos_plan": args.chaos_plan,
        "faults": stats["faults"],
        "requeues": stats["requeues"],
        "shed": stats["shed"],
        "lost_requests": len(errors),
        "errors": errors + clean_errs,
        "mismatched_requests": mismatches,
        "clean_token_p99_s": clean_p99,
        "chaos_token_p99_s": chaos_p99,
        "chaos_p99_bound_s": p99_bound,
        "new_traces_post_warmup": traces[0],
        "new_compiles_post_warmup": traces[1],
        "leaked_pages": leaked,
    }
    _write_summary(args.out_dir, "serving_smoke_chaos.json", summary)

    print("[smoke:chaos] faults {} | requeues {} | token p99 clean "
          "{:.4f}s chaos {:.4f}s (bound {:.4f}s)".format(
              stats["faults"], stats["requeues"], clean_p99, chaos_p99,
              p99_bound))
    failures = []
    if errors or clean_errs:
        failures.append("lost requests: {}".format(errors + clean_errs))
    if mismatches:
        failures.append("requests {} diverged from solo generate() "
                        "after requeue (rng re-base drift)".format(
                            mismatches))
    for kind in ("prefill_fail", "slot_hang", "pool_squeeze"):
        if not stats["faults"].get(kind):
            failures.append("chaos kind {} never fired".format(kind))
    if stats["requeues"] < 2:
        failures.append("expected >= 2 requeues, saw {}".format(
            stats["requeues"]))
    if chaos_p99 > p99_bound:
        failures.append("chaos token p99 {:.4f}s > bound {:.4f}s".format(
            chaos_p99, p99_bound))
    if traces[0] or traces[1]:
        failures.append("retrace during fault recovery ({} traces, {} "
                        "compiles)".format(*traces))
    if leaked:
        failures.append("page refcount leak after chaos drain: {}"
                        .format(leaked))
    return _check(failures, "chaos")


def build_chunked_requests(model, page=16, n_long=5, n_short=8,
                           seed=11):
    """Heavy-prompt mix for the chunked-prefill A/B. The long prompts
    (>= 25% of the fleet) share ONE full-page prefix — a seeder
    request registers it first, so every long is a prefix-cache HIT
    whose near-context-length suffix prefills ON THE TICK THREAD
    (misses prefill on the admission thread, where XLA-CPU overlaps
    them with ticks and no stall is observable on this rig; hits and
    requeues are the tick-resident prefill paths chunking protects).
    The shorts are small prompts with long decodes — the victims whose
    commit-to-commit gaps a monolithic suffix prefill stretches.
    Returns (seeder, requests) — serve the seeder to completion before
    offering the mix, so the longs actually hit.

    Geometry: a hit only survives `_admit_hit`'s fit trim when
    prefix_len + bucket_length(suffix) <= max_seq_len, so the shared
    prefix spans 3 pages and every long suffix stays within a quarter
    of the context — prefix 48 + padded suffix 256 = 304 <= 512. The
    ~200-token suffix keeps the monolithic tick-thread prefill
    expensive relative to one chunk + one tick, which is the contrast
    the gate measures."""
    from cloud_tpu.serving import ServeRequest

    rng = np.random.default_rng(seed)
    shared_len = 3 * page
    shared = rng.integers(1, 512, (shared_len,)).astype(
        np.int32).tolist()
    seeder = ServeRequest(
        prompt=shared + rng.integers(1, 512, (2,)).astype(
            np.int32).tolist(),
        max_new_tokens=2, temperature=0.0, rng_seed=4999)
    long_lo = shared_len + (3 * model.max_seq_len) // 8
    long_hi = shared_len + (13 * model.max_seq_len) // 32
    longs = [(int(rng.integers(long_lo, long_hi)),
              int(rng.integers(2, 5)), True) for _ in range(n_long)]
    shorts = [(int(rng.integers(6, 17)),
               int(rng.integers(24, 33)), False)
              for _ in range(n_short)]
    specs = []
    stride = max(1, n_short // n_long)
    si = 0
    for li in range(n_long):
        specs.extend(shorts[si:si + stride])
        si += stride
        specs.append(longs[li])
    specs.extend(shorts[si:])
    requests = []
    for plen, max_new, is_long in specs:
        tail_len = (plen - shared_len) if is_long else plen
        tail = rng.integers(1, 512, (tail_len,)).astype(
            np.int32).tolist()
        requests.append(ServeRequest(
            prompt=(shared + tail) if is_long else tail,
            max_new_tokens=max_new, temperature=0.0,
            rng_seed=5000 + len(requests)))
    return seeder, requests


def run_chunked(args):
    import jax
    import jax.numpy as jnp

    from cloud_tpu.parallel import runtime
    from cloud_tpu.serving import Scheduler

    model = build_model(max_seq_len=512)
    seeder, requests = build_chunked_requests(model)
    long_cut = model.max_seq_len // 4
    n_long = sum(1 for r in requests if len(r.prompt) >= long_cut)
    assert n_long / len(requests) >= 0.25, "heavy-prompt mix too light"
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    print("[smoke:chunked] solo oracle ({} requests, {} long)".format(
        len(requests), n_long))
    oracle = solo_oracle(model, params, [seeder] + requests)

    def _serve(chunk):
        slots = 4
        pages_per_slot = model.max_seq_len // 16
        scheduler = Scheduler(model, params, slots=slots, page_size=16,
                              num_pages=(slots + 4) * pages_per_slot
                              + 1,
                              admission_window=len(requests),
                              strict_no_retrace=True,
                              prefill_chunk=chunk).start()
        try:
            buckets = sorted({scheduler._bucket(r)
                              for r in [seeder] + requests})
            scheduler.warmup(buckets,
                             sampling_configs=[(("temperature", 0.0),)])
            warm = runtime.compile_stats()
            # Seeder completes (and registers the shared page) before
            # the mix is offered — identically in both legs.
            seed_result = scheduler.submit(
                seeder, timeout=30).result(timeout=600)
            # Open-loop offering at a fixed interval (identical in both
            # legs): an all-at-once burst piles the admission thread's
            # miss prefills into the device queue and every tick-thread
            # fetch behind it stalls — head-of-line noise that buries
            # the A/B signal under cold-start artifacts.
            futures = []
            for req in requests:
                futures.append(scheduler.submit(req, timeout=30))
                time.sleep(0.05)
            results = [f.result(timeout=600) for f in futures]
            after = runtime.compile_stats()
            stats = scheduler.stats()
            time.sleep(0.3)
            scheduler.assert_drained(clear_prefix=True)
            leaked = scheduler.pool.leak_report()
            return [seed_result] + results, stats, leaked, (
                after["n_traces"] - warm["n_traces"],
                after["n_compiles"] - warm["n_compiles"])
        finally:
            scheduler.close()

    print("[smoke:chunked] serve pass (chunked, C={})".format(
        args.chunk_size))
    on_results, on_stats, on_leaked, on_traces = _serve(args.chunk_size)
    print("[smoke:chunked] serve pass (unchunked control)")
    off_results, off_stats, off_leaked, off_traces = _serve(0)

    mism_on = [i for i, (res, ref) in enumerate(zip(on_results, oracle))
               if not np.array_equal(res.tokens, ref)]
    mism_off = [i for i, (res, ref) in enumerate(zip(off_results,
                                                     oracle))
                if not np.array_equal(res.tokens, ref)]
    on_gap = on_stats["decode_gap"].get("p99") or 0.0
    off_gap = off_stats["decode_gap"].get("p99") or 0.0
    gap_ratio = (off_gap / on_gap) if on_gap else 0.0

    summary = {
        "requests": len(requests),
        "long_prompts": n_long,
        "prefix_hits_chunked": on_stats["prefix_hits"],
        "prefix_hits_unchunked": off_stats["prefix_hits"],
        "chunk_size": args.chunk_size,
        "chunks_dispatched": on_stats["prefill_chunks_dispatched"],
        "decode_gap_p99_chunked_s": on_gap,
        "decode_gap_p99_unchunked_s": off_gap,
        "decode_gap_ratio": gap_ratio,
        "min_chunk_gap_ratio": args.min_chunk_gap_ratio,
        "token_p99_chunked_s": on_stats["token_latency"].get("p99"),
        "token_p99_unchunked_s": off_stats["token_latency"].get("p99"),
        "ttft_p50_chunked_s": on_stats["ttft"].get("p50"),
        "ttft_p50_unchunked_s": off_stats["ttft"].get("p50"),
        "new_traces_post_warmup": on_traces[0],
        "new_compiles_post_warmup": on_traces[1],
        "mismatched_chunked": mism_on,
        "mismatched_unchunked": mism_off,
        "leaked_pages": on_leaked or off_leaked,
    }
    _write_summary(args.out_dir, "serving_smoke_chunked.json", summary)

    print("[smoke:chunked] decode-gap p99 unchunked {:.4f}s | chunked "
          "{:.4f}s | ratio {:.2f}x (floor {:.1f}x) | {} chunk "
          "dispatches".format(off_gap, on_gap, gap_ratio,
                              args.min_chunk_gap_ratio,
                              on_stats["prefill_chunks_dispatched"]))
    failures, warnings = [], []
    _gate_ratio(failures, warnings, "decode-gap ratio", gap_ratio,
                args.min_chunk_gap_ratio)
    if not on_stats["prefill_chunks_dispatched"]:
        failures.append("chunked leg dispatched no prefill chunks")
    if (on_stats["prefix_hits"] < n_long
            or off_stats["prefix_hits"] < n_long):
        failures.append(
            "long prompts missed the seeded prefix (hits on={} off={} "
            "< {}): the tick-thread prefill path never ran".format(
                on_stats["prefix_hits"], off_stats["prefix_hits"],
                n_long))
    if off_stats["prefill_chunks_dispatched"]:
        failures.append("unchunked control dispatched {} chunks".format(
            off_stats["prefill_chunks_dispatched"]))
    if on_traces[0] or on_traces[1]:
        failures.append("retrace after warmup with chunking on ({} "
                        "traces, {} compiles)".format(*on_traces))
    if off_traces[0] or off_traces[1]:
        failures.append("retrace after warmup on the control leg ({} "
                        "traces, {} compiles)".format(*off_traces))
    if mism_on or mism_off:
        failures.append("diverged from solo generate(): chunked={} "
                        "unchunked={}".format(mism_on, mism_off))
    if on_leaked or off_leaked:
        failures.append("page refcount leak after drain: on={} off={}"
                        .format(on_leaked, off_leaked))
    return _check(failures, "chunked", warnings)


def run_kvq(args):
    import jax
    import jax.numpy as jnp

    from cloud_tpu.parallel import runtime
    from cloud_tpu.serving import Scheduler, ServeRequest

    # Small vocab on purpose: these weights are random-init, so logits
    # are near-uniform and the top-2 argmax margin shrinks with vocab
    # size (order-statistic spacing) — at 1024 the int8 rounding noise
    # flips coin-toss argmaxes that no trained model exhibits. 128
    # keeps the margins wide enough that the parity gate measures the
    # dequant contract, not the untrained net's ties.
    model = build_model(vocab_size=128)
    page = 16
    pages_per_slot = model.max_seq_len // page
    rng = np.random.default_rng(9)
    requests = [ServeRequest(
        prompt=rng.integers(1, 128, (int(rng.integers(6, 17)),))
        .astype(np.int32).tolist(),
        max_new_tokens=int(rng.integers(8, 17)), temperature=0.0,
        rng_seed=6000 + i) for i in range(12)]
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    print("[smoke:kvq] solo oracle ({} requests)".format(len(requests)))
    oracle = solo_oracle(model, params, requests)

    # Equal HBM byte budget, sized analytically (the engine's
    # page_hbm_bytes contract, asserted against pool.page_bytes below):
    # fp pages are 2 * page * H * D * itemsize per layer; int8 pages
    # shrink the payload to one byte per element and add the per-page
    # per-head f32 scale sidecars.
    head_dim = model.d_model // model.num_heads
    fp_bytes = 2 * page * model.num_heads * head_dim * 4 \
        * model.num_layers
    q_bytes = (2 * page * model.num_heads * head_dim
               + 2 * model.num_heads * 4) * model.num_layers
    fp_pages = 2 * pages_per_slot + 1
    budget = fp_pages * fp_bytes
    q_pages = budget // q_bytes

    def _serve(dtype, num_pages, slots):
        scheduler = Scheduler(model, params, slots=slots,
                              page_size=page, num_pages=num_pages,
                              admission_window=len(requests),
                              strict_no_retrace=True,
                              kv_dtype=dtype).start()
        try:
            buckets = sorted({scheduler._bucket(r) for r in requests})
            scheduler.warmup(buckets,
                             sampling_configs=[(("temperature", 0.0),)])
            warm = runtime.compile_stats()
            results, tokens, secs = run_serve(scheduler, requests)
            after = runtime.compile_stats()
            stats = scheduler.stats()
            time.sleep(0.3)
            scheduler.assert_drained(clear_prefix=True)
            leaked = scheduler.pool.leak_report()
            return results, tokens / secs, stats, leaked, (
                after["n_traces"] - warm["n_traces"],
                after["n_compiles"] - warm["n_compiles"])
        finally:
            scheduler.close()

    print("[smoke:kvq] serve pass (fp pages, {} pages @ {} B)".format(
        fp_pages, fp_bytes))
    fp_results, fp_tps, fp_stats, fp_leaked, fp_traces = _serve(
        "", fp_pages, slots=2)
    print("[smoke:kvq] serve pass (int8 pages, {} pages @ {} B, same "
          "{} B budget)".format(q_pages, q_bytes, budget))
    q_slots = max(2, min(8, q_pages // pages_per_slot))
    q_results, q_tps, q_stats, q_leaked, q_traces = _serve(
        "int8", q_pages, slots=q_slots)

    mism_fp = [i for i, (res, ref) in enumerate(zip(fp_results, oracle))
               if not np.array_equal(res.tokens, ref)]
    mism_q = [i for i, (res, ref) in enumerate(zip(q_results, fp_results))
              if not np.array_equal(res.tokens, ref.tokens)]
    fp_sessions = fp_stats["kv"]["capacity_sessions"]
    q_sessions = q_stats["kv"]["capacity_sessions"]
    capacity_ratio = (q_sessions / fp_sessions) if fp_sessions else 0.0

    summary = {
        "requests": len(requests),
        "hbm_budget_bytes": budget,
        "fp_page_bytes": fp_stats["kv"]["page_bytes"],
        "int8_page_bytes": q_stats["kv"]["page_bytes"],
        "fp_pages": fp_pages,
        "int8_pages": q_pages,
        "fp_capacity_sessions": fp_sessions,
        "int8_capacity_sessions": q_sessions,
        "capacity_ratio": capacity_ratio,
        "min_capacity_ratio": args.min_kvq_capacity_ratio,
        "fp_tokens_per_sec": fp_tps,
        "int8_tokens_per_sec": q_tps,
        "new_traces_post_warmup": q_traces[0],
        "new_compiles_post_warmup": q_traces[1],
        "mismatched_fp_vs_oracle": mism_fp,
        "mismatched_int8_vs_fp": mism_q,
        "leaked_pages": fp_leaked or q_leaked,
    }
    _write_summary(args.out_dir, "serving_smoke_kvq.json", summary)

    print("[smoke:kvq] page bytes fp {} | int8 {} | sessions at {} B: "
          "fp {} int8 {} ({:.2f}x, floor {:.1f}x)".format(
              fp_stats["kv"]["page_bytes"], q_stats["kv"]["page_bytes"],
              budget, fp_sessions, q_sessions, capacity_ratio,
              args.min_kvq_capacity_ratio))
    failures = []
    if fp_stats["kv"]["page_bytes"] != fp_bytes \
            or q_stats["kv"]["page_bytes"] != q_bytes:
        failures.append(
            "pool page_bytes drifted from the analytic formula "
            "(fp {} vs {}, int8 {} vs {})".format(
                fp_stats["kv"]["page_bytes"], fp_bytes,
                q_stats["kv"]["page_bytes"], q_bytes))
    if q_pages * q_bytes > budget:
        failures.append("int8 pool {} B overshoots the {} B budget"
                        .format(q_pages * q_bytes, budget))
    # Capacity is arithmetic, not timing — a miss means the quantized
    # page layout regressed, so the gate is hard at the full floor.
    if capacity_ratio < args.min_kvq_capacity_ratio:
        failures.append(
            "int8 admits only {:.2f}x the fp sessions at an equal "
            "byte budget (floor {:.1f}x)".format(
                capacity_ratio, args.min_kvq_capacity_ratio))
    if mism_fp:
        failures.append("fp requests {} diverged from solo "
                        "generate()".format(mism_fp))
    if mism_q:
        failures.append(
            "int8 requests {} diverged from the fp serve (greedy "
            "parity: quantized pages changed the decode)".format(
                mism_q))
    for tag, traces in (("fp", fp_traces), ("int8", q_traces)):
        if traces[0] or traces[1]:
            failures.append("retrace after warmup on the {} leg ({} "
                            "traces, {} compiles)".format(tag, *traces))
    if fp_leaked or q_leaked:
        failures.append("page refcount leak after drain: fp={} "
                        "int8={}".format(fp_leaked, q_leaked))
    return _check(failures, "kvq")


def build_conversation_sessions(model, n_sessions=4, page=16, seed=13):
    """Multi-turn material for the offload A/B/C. Each session's
    turn-1 prompt spans ~18.5 pages (page-aligned demote keeps 19 full
    pages after an 18-token reply), its turn-2 prompt is the full
    turn-1 output plus an 8-token user tail — so a promoted turn 2
    prefills one suffix bucket instead of ~20 pages (the long prefix
    is what makes the promote-vs-re-prefill contrast structural, not
    a timing accident). The fillers are near-context distinct prompts
    whose admissions churn the small pool and evict resident prefixes
    between the turns. Returns (turn1_requests, tails, fillers);
    turn-2 requests are built at serve time from each leg's own
    turn-1 tokens."""
    from cloud_tpu.serving import ServeRequest

    rng = np.random.default_rng(seed)
    turn1, tails = [], []
    for i in range(n_sessions):
        plen = int(rng.integers(18 * page + 2, 19 * page - 2))
        prompt = rng.integers(1, 512, (plen,)).astype(np.int32).tolist()
        turn1.append(ServeRequest(prompt=prompt, max_new_tokens=18,
                                  temperature=0.0, rng_seed=7000 + i))
        tails.append(rng.integers(1, 512, (8,)).astype(
            np.int32).tolist())
    fillers = [ServeRequest(
        prompt=rng.integers(1, 512, (28 * page,)).astype(
            np.int32).tolist(),
        max_new_tokens=2, temperature=0.0, rng_seed=7500 + i)
        for i in range(2)]
    return turn1, tails, fillers


def run_offload(args):
    import jax
    import jax.numpy as jnp

    from cloud_tpu.parallel import runtime
    from cloud_tpu.serving import Scheduler, ServeRequest

    model = build_model(max_seq_len=512)
    page = 16
    pages_per_slot = model.max_seq_len // page
    turn1, tails, fillers = build_conversation_sessions(model, page=page)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    # Two sessions' worth of pages: too small to keep every session's
    # prefix resident on-device, so the filler churn (plus the explicit
    # clear below, which pins the A/B/C classes exactly) evicts them.
    small_pool = 2 * pages_per_slot + 1
    ample_pool = 8 * pages_per_slot + 1

    def _serve(host_tier, num_pages, evict, corrupt=False):
        scheduler = Scheduler(model, params, slots=2, page_size=page,
                              num_pages=num_pages, admission_window=4,
                              strict_no_retrace=True,
                              host_tier=host_tier).start()
        try:
            scheduler.warmup([model.max_seq_len],
                             sampling_configs=[(("temperature", 0.0),)])
            warm = runtime.compile_stats()
            t1 = [scheduler.submit(r, timeout=30).result(timeout=600)
                  for r in turn1]
            if evict:
                # Organic pressure first (the filler admissions churn
                # the small pool's LRU), then the explicit clear: the
                # gates below need EVERY turn-2 in its leg's class, not
                # whichever prefixes the LRU happened to spare.
                for f in fillers:
                    scheduler.submit(f, timeout=30).result(timeout=600)
                scheduler.trie.clear()
            if corrupt:
                for entry in scheduler.host_tier._entries.values():
                    entry["digest"] = "deadbeef"
            turn2 = [ServeRequest(
                prompt=np.asarray(res.tokens).tolist() + tails[i],
                max_new_tokens=4, temperature=0.0, rng_seed=7100 + i)
                for i, res in enumerate(t1)]
            t2 = [scheduler.submit(r, timeout=30).result(timeout=600)
                  for r in turn2]
            after = runtime.compile_stats()
            stats = scheduler.stats()
            time.sleep(0.3)
            scheduler.assert_drained(clear_prefix=True)
            leaked = scheduler.pool.leak_report()
            return t1, t2, stats, leaked, (
                after["n_traces"] - warm["n_traces"],
                after["n_compiles"] - warm["n_compiles"])
        finally:
            scheduler.close()

    print("[smoke:offload] leg A: host tier, {}-page pool (promote "
          "path)".format(small_pool))
    a_t1, a_t2, a_stats, a_leaked, a_traces = _serve(
        True, small_pool, evict=True)
    print("[smoke:offload] leg B: ample {}-page pool (device-hit "
          "control)".format(ample_pool))
    b_t1, b_t2, b_stats, b_leaked, b_traces = _serve(
        False, ample_pool, evict=False)
    print("[smoke:offload] leg C: no host tier, {}-page pool "
          "(re-prefill control)".format(small_pool))
    c_t1, c_t2, c_stats, c_leaked, c_traces = _serve(
        False, small_pool, evict=True)
    print("[smoke:offload] leg D: host tier with corrupted digests "
          "(typed fallback)")
    d_t1, d_t2, d_stats, _, _ = _serve(
        True, small_pool, evict=True, corrupt=True)

    t2_offload = float(np.median([r.ttft_s for r in a_t2]))
    t2_hit = float(np.median([r.ttft_s for r in b_t2]))
    t2_reprefill = float(np.median([r.ttft_s for r in c_t2]))
    # "offload <= 1.5x device hit" recast as a floor for _gate_ratio:
    # headroom 1.0 means exactly 1.5x; below HARD_GATE_FRACTION the
    # promote path costs > 2.5x a device hit and the tier is broken.
    hit_headroom = (args.max_offload_hit_factor * t2_hit / t2_offload
                    if t2_offload else 0.0)
    reprefill_ratio = (t2_reprefill / t2_offload) if t2_offload else 0.0

    n = len(turn1)
    mism_t1 = [i for i in range(n)
               if not (np.array_equal(a_t1[i].tokens, b_t1[i].tokens)
                       and np.array_equal(a_t1[i].tokens,
                                          c_t1[i].tokens)
                       and np.array_equal(a_t1[i].tokens,
                                          d_t1[i].tokens))]
    mism_t2 = [i for i in range(n)
               if not (np.array_equal(a_t2[i].tokens, b_t2[i].tokens)
                       and np.array_equal(a_t2[i].tokens,
                                          c_t2[i].tokens)
                       and np.array_equal(a_t2[i].tokens,
                                          d_t2[i].tokens))]

    summary = {
        "sessions": n,
        "small_pool_pages": small_pool,
        "ample_pool_pages": ample_pool,
        "ttft_turn2_offload_p50_s": t2_offload,
        "ttft_turn2_device_hit_p50_s": t2_hit,
        "ttft_turn2_reprefill_p50_s": t2_reprefill,
        "hit_headroom": hit_headroom,
        "max_offload_hit_factor": args.max_offload_hit_factor,
        "reprefill_ratio": reprefill_ratio,
        "min_reprefill_ratio": args.min_offload_reprefill_ratio,
        "offload_demotes": a_stats["kv"]["page_demotes"],
        "offload_promotes": a_stats["kv"]["page_promotes"],
        "offload_turn2_prefix_lens": [r.prefix_len for r in a_t2],
        "reprefill_turn2_prefix_lens": [r.prefix_len for r in c_t2],
        "digest_failures": d_stats["kv"]["digest_failures"],
        "digest_leg_promotes": d_stats["kv"]["page_promotes"],
        "digest_leg_faults": d_stats["faults"],
        "mismatched_turn1": mism_t1,
        "mismatched_turn2": mism_t2,
        "new_traces_post_warmup": a_traces[0],
        "new_compiles_post_warmup": a_traces[1],
        "leaked_pages": a_leaked or b_leaked or c_leaked,
    }
    _write_summary(args.out_dir, "serving_smoke_offload.json", summary)

    print("[smoke:offload] turn-2 TTFT p50: promote {:.4f}s | device "
          "hit {:.4f}s | re-prefill {:.4f}s (<= {:.1f}x hit, >= "
          "{:.1f}x over re-prefill)".format(
              t2_offload, t2_hit, t2_reprefill,
              args.max_offload_hit_factor,
              args.min_offload_reprefill_ratio))
    print("[smoke:offload] demotes {} | promotes {} | digest "
          "fallbacks {}".format(a_stats["kv"]["page_demotes"],
                                a_stats["kv"]["page_promotes"],
                                d_stats["kv"]["digest_failures"]))
    failures, warnings = [], []
    _gate_ratio(failures, warnings, "offload-vs-hit TTFT headroom",
                hit_headroom, 1.0)
    _gate_ratio(failures, warnings, "re-prefill/offload TTFT ratio",
                reprefill_ratio, args.min_offload_reprefill_ratio)
    if a_stats["kv"]["page_promotes"] < n:
        failures.append(
            "only {} promote admissions for {} follow-up turns (the "
            "evicted prefixes were not served from the host "
            "tier)".format(a_stats["kv"]["page_promotes"], n))
    if any(r.prefix_len < 18 * page for r in a_t2):
        failures.append(
            "offload-leg turn-2 prefix lens {} below the demoted "
            "prefix (promote served fewer pages than the tier "
            "held)".format([r.prefix_len for r in a_t2]))
    if any(r.prefix_len != 0 for r in c_t2):
        failures.append(
            "re-prefill control served prefixes {} (eviction did not "
            "take; the C leg is not measuring a cold turn 2)".format(
                [r.prefix_len for r in c_t2]))
    if d_stats["kv"]["digest_failures"] < n:
        failures.append(
            "{} digest fallbacks for {} corrupted entries (stale "
            "host pages were served)".format(
                d_stats["kv"]["digest_failures"], n))
    if d_stats["kv"]["page_promotes"]:
        failures.append("{} promotes on the corrupt-digest leg "
                        "(corrupt pages must never map in)".format(
                            d_stats["kv"]["page_promotes"]))
    if not d_stats["faults"].get("host_tier_corrupt"):
        failures.append("digest mismatch raised no typed "
                        "host_tier_corrupt fault")
    if mism_t1 or mism_t2:
        failures.append(
            "sessions diverged across legs (turn1={} turn2={}): "
            "promoted pages or the fallback changed the decode".format(
                mism_t1, mism_t2))
    for tag, traces in (("offload", a_traces), ("device-hit", b_traces),
                        ("re-prefill", c_traces)):
        if traces[0] or traces[1]:
            failures.append("retrace after warmup on the {} leg ({} "
                            "traces, {} compiles)".format(tag, *traces))
    if a_leaked or b_leaked or c_leaked:
        failures.append("page refcount leak after drain: A={} B={} "
                        "C={}".format(a_leaked, b_leaked, c_leaked))
    return _check(failures, "offload", warnings)


def run_autoscale(args):
    import dataclasses

    import jax
    import jax.numpy as jnp

    from cloud_tpu.parallel import runtime
    from cloud_tpu.serving import Scheduler, admission, reqtrace
    from cloud_tpu.serving.loadgen import (DiurnalSpec, build_diurnal,
                                           run_diurnal)

    slots_lo = args.autoscale_slots_min
    slots_hi = args.autoscale_slots_max
    model = build_model()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    # Heavier decodes than the LoadSpec defaults: a request holds its
    # slot ~16 ticks, so the fixed leg's capacity sits BELOW the crest
    # rate on any rig speed — the contrast the A/B measures is the
    # geometry, not rig luck. Prefix sharing keeps the resize+gather
    # composition under live fire.
    spec = DiurnalSpec(rate_lo=2.0, rate_hi=args.autoscale_rate_hi,
                       segments=5, segment_s=1.5,
                       max_new_lo=8, max_new_hi=24,
                       shared_prefix_ratio=0.3, seed=7)
    entries = build_diurnal(spec, model.vocab_size, model.max_seq_len)
    requests = [e[2] for e in entries]
    print("[smoke:autoscale] solo oracle ({} requests, {} segments)"
          .format(len(requests), spec.segments))
    oracle = solo_oracle(model, params, requests)

    # The fixed leg's reqtrace feeds the admission fit; both legs' re-
    # size events land in the same artifact for collect --serve.
    os.environ.setdefault("CLOUD_TPU_REQTRACE", "1")
    os.environ.setdefault("CLOUD_TPU_REQTRACE_DIR", args.out_dir)
    pages_per_slot = model.max_seq_len // 16

    def _leg(tag, slo, **kwargs):
        """One A/B leg. `slo=None` calibrates the TTFT SLO on THIS
        warmed, idle leg — the median of three unloaded probes times
        AUTOSCALE_SLO_MULT — so the gate tracks the rig's actual speed
        instead of a wall-clock constant (CI containers vary 10x). The
        fixed leg calibrates; the elastic leg reuses its SLO, so both
        legs are scored against the identical target."""
        scheduler = Scheduler(model, params, page_size=16,
                              admission_window=slots_hi,
                              strict_no_retrace=True,
                              **kwargs).start()
        try:
            print("[smoke:autoscale] {} leg warmup (ladder {})".format(
                tag, list(scheduler.engine.ladder)))
            scheduler.warmup(sorted({scheduler._bucket(r)
                                     for r in requests}),
                             sampling_configs=[(("temperature",
                                                 0.0),)])
            if slo is None:
                probes = []
                for j in range(3):
                    probe = dataclasses.replace(requests[0],
                                                rng_seed=9000 + j)
                    res = scheduler.submit(
                        probe, timeout=30).result(timeout=120)
                    probes.append(res.ttft_s)
                slo = args.autoscale_slo_mult * sorted(probes)[1]
                print("[smoke:autoscale] calibrated slo_ttft "
                      "{:.4f}s ({}x unloaded ttft {:.4f}s)".format(
                          slo, args.autoscale_slo_mult,
                          sorted(probes)[1]))
            # Arm the shed-admission gate (and the learned predictor,
            # when loaded) with the calibrated SLO.
            scheduler._slo_ttft = slo
            warm = runtime.compile_stats()
            print("[smoke:autoscale] {} leg serve pass".format(tag))
            run = run_diurnal(scheduler, spec, slo_ttft=slo,
                              keep_tokens=True)
            after = runtime.compile_stats()
            stats = scheduler.stats()
        finally:
            scheduler.close()
        mismatches = [r["i"] for r in run["per_request"]
                      if r.get("tokens") is not None
                      and r["tokens"] != [int(t)
                                          for t in oracle[r["i"]]]]
        return {
            "slo_ttft_s": slo,
            "goodput": run["goodput"],
            "good": run["good"],
            "offered": run["offered"],
            "completed": run["completed"],
            "shed": run["shed"],
            "rejected": run["rejected"],
            "worst_ttft_p99": run["worst_ttft_p99"],
            "offered_curve": [
                {k: v for k, v in seg.items()}
                for seg in run["offered_curve"]],
            "resizes": stats["geometry"]["resizes"],
            "resize_events": stats["geometry"]["resize_events"],
            "per_geometry": {
                rung: {"ticks": g["ticks"],
                       "occupancy_mean": g["occupancy_mean"]}
                for rung, g in stats["geometry"]["per_geometry"]
                .items()},
            "admission_predictor": stats["admission_predictor"],
            "mismatched_requests": mismatches,
            "new_traces_post_warmup": (after["n_traces"]
                                       - warm["n_traces"]),
            "new_compiles_post_warmup": (after["n_compiles"]
                                         - warm["n_compiles"]),
        }

    slo = args.autoscale_slo_ttft or None  # 0 = calibrate on the rig
    fixed = _leg("fixed", slo, slots=slots_lo,
                 num_pages=(slots_lo + 4) * pages_per_slot + 1)
    slo = fixed["slo_ttft_s"]

    # Fit the admission predictor from the corpus the fixed leg just
    # wrote; the elastic leg loads it at start() — the full offline
    # fit -> serve-time predict loop inside one smoke run.
    model_path = None
    tracer = reqtrace.get()
    if tracer is not None:
        tracer.flush()
        try:
            doc = admission.fit([tracer.path])
            model_path = os.path.join(args.out_dir,
                                      "admission_model.json")
            with open(model_path, "w") as fh:
                json.dump(doc, fh, indent=2, sort_keys=True)
            print("[smoke:autoscale] fit admission model: phases {}"
                  .format(sorted(doc["phases"])))
        except ValueError as exc:
            print("[smoke:autoscale] admission fit skipped: "
                  "{}".format(exc))

    auto = _leg("auto", slo, slots=slots_lo, slots_min=slots_lo,
                slots_max=slots_hi,
                num_pages=(slots_hi + 4) * pages_per_slot + 1,
                resize_quiet_ticks=args.autoscale_quiet_ticks,
                admission_model=model_path)

    goodput_ratio = auto["goodput"] / max(fixed["goodput"], 1e-9)
    p99_parity = None
    if auto["worst_ttft_p99"] and fixed["worst_ttft_p99"]:
        p99_parity = fixed["worst_ttft_p99"] / auto["worst_ttft_p99"]
    summary = {
        "spec": {"rate_lo": spec.rate_lo, "rate_hi": spec.rate_hi,
                 "segments": spec.segments,
                 "segment_s": spec.segment_s, "seed": spec.seed,
                 "slo_ttft_s": slo},
        "ladder": {"min": slots_lo, "max": slots_hi},
        "fixed": fixed,
        "auto": auto,
        "goodput_ratio": goodput_ratio,
        "min_goodput_ratio": args.min_autoscale_goodput,
        "worst_p99_parity": p99_parity,
        "p99_factor": args.autoscale_p99_factor,
        "admission_model": model_path,
    }
    _write_summary(args.out_dir, "serving_smoke_autoscale.json",
                   summary)

    print("[smoke:autoscale] goodput fixed {:.3f} vs auto {:.3f} "
          "({:.2f}x, floor {:.1f}x)".format(
              fixed["goodput"], auto["goodput"], goodput_ratio,
              args.min_autoscale_goodput))
    print("[smoke:autoscale] worst seg ttft p99 fixed {} vs auto {} | "
          "auto resizes {}".format(fixed["worst_ttft_p99"],
                                   auto["worst_ttft_p99"],
                                   auto["resizes"]))
    failures, warnings = [], []
    _gate_ratio(failures, warnings, "autoscale goodput",
                goodput_ratio, args.min_autoscale_goodput)
    if p99_parity is None:
        failures.append("worst-case p99 missing on a leg (fixed {}, "
                        "auto {})".format(fixed["worst_ttft_p99"],
                                          auto["worst_ttft_p99"]))
    else:
        # "At equal worst-case p99": the elastic leg may not buy its
        # goodput by letting the tail rot — its worst per-segment p99
        # stays within AUTOSCALE_P99_FACTOR of the fixed leg's.
        _gate_ratio(failures, warnings, "worst-case p99 parity",
                    p99_parity, 1.0 / args.autoscale_p99_factor)
    if auto["resizes"]["grow"] < 1 or auto["resizes"]["shrink"] < 1:
        failures.append("elastic leg must fire >= 1 grow and >= 1 "
                        "shrink; got {}".format(auto["resizes"]))
    if fixed["resizes"]["grow"] or fixed["resizes"]["shrink"]:
        failures.append("fixed leg resized: {}".format(
            fixed["resizes"]))
    for tag, leg in (("fixed", fixed), ("auto", auto)):
        if leg["mismatched_requests"]:
            failures.append("{} leg requests {} diverged from solo "
                            "generate()".format(
                                tag, leg["mismatched_requests"]))
        if leg["new_traces_post_warmup"] or \
                leg["new_compiles_post_warmup"]:
            failures.append("{} leg retraced after warmup ({} traces,"
                            " {} compiles)".format(
                                tag, leg["new_traces_post_warmup"],
                                leg["new_compiles_post_warmup"]))
    if model_path is not None and \
            not auto["admission_predictor"]["loaded"]:
        failures.append("admission model written but not loaded: "
                        "{}".format(auto["admission_predictor"]))
    return _check(failures, "autoscale", warnings)


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out-dir", default=os.environ.get(
        "CLOUD_TPU_TELEMETRY_DIR", "serving-smoke-out"))
    parser.add_argument("--scenario", default="base",
                        choices=["base", "prefix", "spec", "chaos",
                                 "chunked", "kvq", "offload",
                                 "autoscale", "all"])
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--spec-k", type=int, default=3)
    parser.add_argument("--chunk-size", type=int, default=int(
        os.environ.get("CLOUD_TPU_SERVE_PREFILL_CHUNK", 0)
        or CHUNK_SIZE))
    parser.add_argument("--min-chunk-gap-ratio", type=float,
                        default=float(os.environ.get(
                            "CLOUD_TPU_SMOKE_MIN_CHUNK_GAP",
                            MIN_CHUNK_GAP_RATIO)))
    parser.add_argument("--min-speedup", type=float, default=float(
        os.environ.get("CLOUD_TPU_SMOKE_MIN_SPEEDUP", MIN_SPEEDUP)))
    parser.add_argument("--min-ttft-ratio", type=float, default=float(
        os.environ.get("CLOUD_TPU_SMOKE_MIN_TTFT_RATIO",
                       MIN_TTFT_RATIO)))
    parser.add_argument("--min-spec-speedup", type=float, default=float(
        os.environ.get("CLOUD_TPU_SMOKE_MIN_SPEC_SPEEDUP",
                       MIN_SPEC_SPEEDUP)))
    parser.add_argument("--chaos-plan", default=CHAOS_PLAN)
    parser.add_argument("--chaos-p99-factor", type=float, default=float(
        os.environ.get("CLOUD_TPU_SMOKE_CHAOS_P99_FACTOR",
                       CHAOS_P99_FACTOR)))
    parser.add_argument("--min-kvq-capacity-ratio", type=float,
                        default=float(os.environ.get(
                            "CLOUD_TPU_SMOKE_MIN_KVQ_CAPACITY",
                            MIN_KVQ_CAPACITY_RATIO)))
    parser.add_argument("--max-offload-hit-factor", type=float,
                        default=float(os.environ.get(
                            "CLOUD_TPU_SMOKE_MAX_OFFLOAD_HIT",
                            MAX_OFFLOAD_HIT_FACTOR)))
    parser.add_argument("--min-offload-reprefill-ratio", type=float,
                        default=float(os.environ.get(
                            "CLOUD_TPU_SMOKE_MIN_OFFLOAD_REPREFILL",
                            MIN_OFFLOAD_REPREFILL_RATIO)))
    parser.add_argument("--min-autoscale-goodput", type=float,
                        default=float(os.environ.get(
                            "CLOUD_TPU_SMOKE_MIN_AUTOSCALE_GOODPUT",
                            MIN_AUTOSCALE_GOODPUT)))
    parser.add_argument("--autoscale-p99-factor", type=float,
                        default=float(os.environ.get(
                            "CLOUD_TPU_SMOKE_AUTOSCALE_P99_FACTOR",
                            AUTOSCALE_P99_FACTOR)))
    parser.add_argument("--autoscale-rate-hi", type=float,
                        default=float(os.environ.get(
                            "CLOUD_TPU_SMOKE_AUTOSCALE_RATE_HI",
                            AUTOSCALE_RATE_HI)))
    parser.add_argument("--autoscale-slots-min", type=int, default=2)
    parser.add_argument("--autoscale-slots-max", type=int, default=8)
    parser.add_argument("--autoscale-slo-ttft", type=float,
                        default=float(os.environ.get(
                            "CLOUD_TPU_SMOKE_AUTOSCALE_SLO_TTFT",
                            0.0)))  # 0 = calibrate from unloaded ttft
    parser.add_argument("--autoscale-slo-mult", type=float,
                        default=float(os.environ.get(
                            "CLOUD_TPU_SMOKE_AUTOSCALE_SLO_MULT",
                            AUTOSCALE_SLO_MULT)))
    # Low enough that the post-crest ramp-down still shrinks inside
    # the run, high enough that a one-tick lull at the crest does not
    # shed a rung it immediately needs back (the re-grow straggler
    # inflates the worst-segment p99).
    parser.add_argument("--autoscale-quiet-ticks", type=int,
                        default=12)
    args = parser.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    scenarios = {"base": [run_base], "prefix": [run_prefix],
                 "spec": [run_spec], "chaos": [run_chaos],
                 "chunked": [run_chunked], "kvq": [run_kvq],
                 "offload": [run_offload],
                 "autoscale": [run_autoscale],
                 "all": [run_base, run_prefix, run_spec, run_chaos,
                         run_chunked, run_kvq, run_offload,
                         run_autoscale]}[args.scenario]
    rc = 0
    for scenario in scenarios:
        rc = scenario(args) or rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
