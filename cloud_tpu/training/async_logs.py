"""The async host loop: off-thread metric readback with lazy logs.

The fit loop's steady state must never block on the device. PR 1
removed the host->device stalls (`cache="device"`); this module removes
the device->host ones. Three pieces:

- `MetricFuture`: the handle the train loop gets back immediately when
  it hands an epoch's device-scalar logs off for readback. `result()`
  blocks until the background fetch lands (or re-raises the fetch
  error); `done()` never blocks.
- `AsyncMetricReader`: a bounded-queue background thread that performs
  the actual fetch — ONE coalesced `runtime.device_fetch` per
  submitted pytree (one tunnel round trip per logging interval, the
  counted invariant), then `float()`s the already-host leaves for
  free. The queue is bounded so a slow host can exert backpressure
  instead of accumulating device log buffers; errors are re-raised on
  the submitting thread at the NEXT boundary (`submit` raises) and on
  `result()`, so a poisoned fetch can't be silently dropped.
- `LazyLogs`: the dict handed to callbacks. Host-side entries
  (steps_per_sec, val_* floats) are ordinary items; device-metric
  entries stay PENDING until something actually reads one — then the
  whole future resolves at once (it was one coalesced fetch; there is
  no per-key laziness to exploit). Callbacks that only write
  (`logs["lr"] = ...`) or never touch device keys never wait at all.

Why floats and not 0-d numpy: every existing consumer (History lists,
EarlyStopping comparisons, MetricsLogger's json.dumps) expects plain
Python floats, and `float()` on an already-fetched numpy scalar is
free — the laziness lives in the fetch, not the conversion.
"""

import queue
import threading

from ..parallel import runtime

__all__ = ["MetricFuture", "AsyncMetricReader", "LazyLogs"]


class MetricFuture:
    """A one-shot future for a fetched metrics dict.

    Deliberately tiny (not concurrent.futures.Future): no
    cancellation, no callbacks racing the resolver — just an Event and
    a slot, because the reader thread is the only writer and the train
    loop the only reader.
    """

    __slots__ = ("_event", "_value", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error = None

    def set_result(self, value):
        self._value = value
        self._event.set()

    def set_exception(self, exc):
        self._error = exc
        self._event.set()

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        """The fetched `{name: float}` dict; re-raises the fetch error.

        `timeout` only bounds the wait for the background fetch; the
        default (None) waits forever, which is correct for the train
        loop — the fetch is already in flight and the device will
        answer or error.
        """
        if not self._event.wait(timeout):
            raise TimeoutError("metric fetch did not complete within "
                               "{}s".format(timeout))
        if self._error is not None:
            raise self._error
        return self._value


# Queue depth 2: the fetch for epoch N overlaps training of epoch N+1,
# and one more slot absorbs jitter. Deeper would let a wedged tunnel
# hide arbitrarily many unfetched epochs before backpressure surfaces
# it; shallower (1) would serialize submit against the in-flight fetch.
_QUEUE_DEPTH = 2

_CLOSE = object()   # sentinel: reader thread exits after draining


class AsyncMetricReader:
    """Background device->host reader with a bounded queue of futures.

    `submit(device_logs)` enqueues one pytree of device scalars and
    returns a `MetricFuture` immediately; the daemon thread performs
    ONE `runtime.device_fetch` per submission (the counted one-round-
    trip-per-interval invariant) and resolves the future with
    `{name: float}`. If a previous fetch errored, the error re-raises
    here — on the submitting (train) thread, at the next boundary —
    as well as on that future's `result()`.
    """

    def __init__(self, maxsize=_QUEUE_DEPTH):
        self._queue = queue.Queue(maxsize=maxsize)
        self._thread = None
        self._lock = threading.Lock()
        self._pending_error = None

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="cloud-tpu-metric-reader",
                    daemon=True)
                self._thread.start()

    def _run(self):
        # Label this thread for the graftsan sanitizer: fetches here
        # are the sanctioned off-thread readback, not step-loop syncs.
        runtime.set_phase("async_reader")
        while True:
            item = self._queue.get()
            if item is _CLOSE:
                return
            device_logs, future = item
            try:
                from cloud_tpu.monitoring import spans

                # graftscope: one span per off-thread drain — this is
                # the time the reader thread spends resolving an
                # interval, invisible to the step loop by design.
                with spans.span("async_reader_drain"):
                    host = runtime.device_fetch(device_logs)
                future.set_result({k: float(v)
                                   for k, v in host.items()})
            except BaseException as exc:  # propagate, never swallow
                future.set_exception(exc)
                with self._lock:
                    if self._pending_error is None:
                        self._pending_error = exc

    def submit(self, device_logs):
        """Enqueue one logging interval's device scalars; returns a
        MetricFuture. Raises a PREVIOUS interval's fetch error if one
        is pending — the poisoned-fetch propagation boundary."""
        with self._lock:
            err, self._pending_error = self._pending_error, None
        if err is not None:
            raise err
        self._ensure_thread()
        future = MetricFuture()
        self._queue.put((device_logs, future))
        return future

    def drain(self):
        """Blocks until every submitted fetch has resolved.

        Drains via a marker submission: the FIFO queue guarantees the
        marker resolves only after everything ahead of it (polling
        queue emptiness would race the in-flight fetch).
        """
        marker = MetricFuture()
        self._ensure_thread()
        self._queue.put(({}, marker))
        marker.result()

    def close(self):
        """Stops the reader thread after the queue drains. Idempotent;
        a closed reader restarts lazily on the next submit."""
        with self._lock:
            thread = self._thread
        if thread is None or not thread.is_alive():
            return
        self._queue.put(_CLOSE)
        thread.join()


class LazyLogs(dict):
    """The callback-facing logs dict: host items eager, device items
    pending until first read.

    Construction takes the `MetricFuture` for the interval's device
    metrics (plus their key names, so membership tests don't force the
    fetch) and any already-host items. Reads of a pending key —
    `logs["loss"]`, `logs.get`, `items()`, iteration, `len`, `in` on a
    resolved-away key — resolve the WHOLE future (it was one coalesced
    fetch). Writes never resolve: `logs["lr"] = 0.1` is what schedule
    callbacks do every epoch and must stay free. A callback that
    overwrites a pending key before anything read it wins — resolution
    fills via `setdefault`, preserving the Keras contract that later
    callbacks see earlier callbacks' mutations.
    """

    def __init__(self, future=None, device_keys=(), host_items=None):
        super().__init__(host_items or {})
        self._future = future
        self._device_keys = tuple(device_keys)

    def _resolve(self):
        future, self._future = self._future, None
        if future is None:
            return
        for key, value in future.result().items():
            # setdefault: a pre-resolution callback write wins.
            self.setdefault(key, value)
        self._device_keys = ()

    def pending_keys(self):
        """Device-metric names not yet materialized (non-resolving)."""
        if self._future is None:
            return ()
        return tuple(k for k in self._device_keys
                     if not dict.__contains__(self, k))

    def __missing__(self, key):
        if self._future is not None:
            self._resolve()
            if dict.__contains__(self, key):
                return dict.__getitem__(self, key)
        raise KeyError(key)

    def __contains__(self, key):
        return dict.__contains__(self, key) or key in self.pending_keys()

    def get(self, key, default=None):
        if dict.__contains__(self, key):
            return dict.__getitem__(self, key)
        if key in self.pending_keys():
            self._resolve()
            return dict.get(self, key, default)
        return default

    def __len__(self):
        return dict.__len__(self) + len(self.pending_keys())

    def __iter__(self):
        self._resolve()
        return dict.__iter__(self)

    def keys(self):
        self._resolve()
        return dict.keys(self)

    def values(self):
        self._resolve()
        return dict.values(self)

    def items(self):
        self._resolve()
        return dict.items(self)

    def __eq__(self, other):
        self._resolve()
        return dict.__eq__(self, other)

    def __ne__(self, other):
        result = self.__eq__(other)
        return result if result is NotImplemented else not result

    __hash__ = None

    def copy(self):
        self._resolve()
        return dict(dict.items(self))

    def __repr__(self):
        # repr must NOT force the fetch (progress/debug printing of a
        # still-pending logs dict would defeat the laziness).
        pending = self.pending_keys()
        if pending:
            return "LazyLogs({}, pending={})".format(
                dict.__repr__(self), list(pending))
        return dict.__repr__(self)
