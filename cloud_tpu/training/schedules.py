"""Learning-rate schedule helpers.

Thin, named constructors over optax schedules for the patterns TPU
training actually uses (the reference leaves schedules to Keras; these
are the optax-native equivalents). Every helper returns an optax
schedule — pass it as the learning rate of any optax optimizer:

    tx = optax.adamw(schedules.warmup_cosine(3e-4, total_steps=10_000))
    Trainer(model, optimizer=tx, ...)
"""

import optax


def warmup_cosine(peak_lr, total_steps, warmup_steps=None, end_lr=0.0):
    """Linear warmup to `peak_lr`, cosine decay to `end_lr`.

    The default LLM/vision pretraining shape. `warmup_steps` defaults
    to 10% of `total_steps`.
    """
    if warmup_steps is None:
        warmup_steps = max(total_steps // 10, 1)
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=peak_lr,
        warmup_steps=warmup_steps, decay_steps=total_steps,
        end_value=end_lr)


def warmup_linear(peak_lr, total_steps, warmup_steps=None, end_lr=0.0):
    """Linear warmup then linear decay — the BERT fine-tuning shape."""
    if warmup_steps is None:
        warmup_steps = max(total_steps // 10, 1)
    return optax.join_schedules(
        [optax.linear_schedule(0.0, peak_lr, warmup_steps),
         optax.linear_schedule(peak_lr, end_lr,
                               max(total_steps - warmup_steps, 1))],
        boundaries=[warmup_steps])


def inverse_sqrt(peak_lr, warmup_steps=1000):
    """Noam/Transformer schedule: linear warmup, then 1/sqrt(step)."""

    def schedule(step):
        import jax.numpy as jnp

        s = jnp.asarray(step, jnp.float32) + 1.0
        warm = peak_lr * s / warmup_steps
        decay = peak_lr * (warmup_steps ** 0.5) / jnp.sqrt(s)
        return jnp.minimum(warm, decay)

    return schedule


def constant(lr):
    """A constant schedule (symmetry with the named shapes)."""
    return optax.constant_schedule(lr)
