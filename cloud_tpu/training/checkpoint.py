"""Checkpoint/restore of train state via orbax.

The reference delegates checkpointing to Keras SavedModel + callbacks on
GCS, with a decoy-directory workaround so non-chief MWMS workers don't
corrupt the real save (reference cloud_fit/remote.py:130-145). Orbax's
single-writer protocol replaces that workaround; the per-step directory
layout (`<dir>/<step>`) keeps the tuner's per-trial checkpoint convention
(reference tuner/tuner.py:601-605).
"""

import hashlib
import json
import logging
import os
import sys
import threading
import time

import jax
import orbax.checkpoint as ocp

from cloud_tpu.utils import storage

logger = logging.getLogger("cloud_tpu")

#: Sidecar filename suffix: `<dir>/<step>.meta.json` rides next to the
#: orbax step directory. `latest_step`'s digit scan never sees it, and
#: orbax's own `force=True` directory replacement never touches it.
METADATA_SUFFIX = ".meta.json"


def _checkpointer():
    return ocp.StandardCheckpointer()


_async_checkpointer = None
# In-flight async save bookkeeping: orbax already serializes saves
# through the single AsyncCheckpointer, but it does NOT guard two
# logical saves racing to the SAME <dir>/<step> path (a preemption
# re-save, a callback firing twice) — the second would start committing
# over the first's partially-written directory. The guard makes that a
# wait-then-write, and gives tests/Trainer an introspection point
# (`pending_saves()`), so a crash window can never leave a torn
# checkpoint that a later `latest_step` would pick up.
_pending_lock = threading.Lock()
_pending_paths = set()


def _get_async_checkpointer():
    # One process-wide AsyncCheckpointer: it owns the background write
    # thread, and orbax serializes saves through it (a second save waits
    # for the first), so per-save construction would forfeit the async.
    global _async_checkpointer
    if _async_checkpointer is None:
        _async_checkpointer = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())
    return _async_checkpointer


def wait_until_finished():
    """Blocks until every async save has committed. No-op when none are
    pending. Call before reading a checkpoint written with
    `save(..., use_async=True)` or at end of training. (Trainer.fit
    calls this on every exit path — normal return, EarlyStopping abort,
    or a raising train step — so fit never returns with a write still
    in flight.)"""
    if _async_checkpointer is not None:
        _async_checkpointer.wait_until_finished()
    with _pending_lock:
        _pending_paths.clear()


def pending_saves():
    """Snapshot of `<dir>/<step>` paths with an async save in flight
    (empty after wait_until_finished)."""
    with _pending_lock:
        return frozenset(_pending_paths)


def _host_snapshot(state):
    """Donation-safe copy of `state` for a background write.

    The train step donates its state buffers (`donate_argnums=0`):
    letting orbax serialize the LIVE device arrays while the next step
    runs would race the donation — the step could rewrite (or
    invalidate) the very buffers the writer thread is reading, tearing
    the checkpoint. One instrumented coalesced device_get pins the
    bytes on the host first; the write then proceeds from memory no
    future step can touch. Only fully-addressable trees snapshot —
    multi-host shardings keep the device arrays so orbax's distributed
    serialization protocol (which coordinates its own copy) still
    applies.
    """
    from cloud_tpu.parallel import runtime

    leaves = [l for l in jax.tree_util.tree_leaves(state)
              if isinstance(l, jax.Array)]
    if leaves and all(l.is_fully_addressable for l in leaves):
        # Phase label for the graftsan sanitizer: this coalesced fetch
        # is the sanctioned snapshot copy, whatever thread saves from.
        from cloud_tpu.monitoring import spans

        previous = runtime.set_phase("checkpoint")
        try:
            # graftscope: the snapshot copy is its own span so the
            # step-time breakdown can separate checkpoint stalls from
            # ordinary boundary fetches.
            with spans.span("checkpoint_snapshot"):
                return runtime.device_fetch(state)
        finally:
            runtime.set_phase(previous)
    return state


def _normalize(directory):
    """Local paths become absolute (orbax requires it); gs:// URIs pass
    through untouched — tensorstore reads/writes them directly."""
    if storage.is_gcs_path(directory):
        return str(directory).rstrip("/")
    return os.path.abspath(directory)


def tree_digest(tree):
    """sha256 content digest of a pytree: structure plus every leaf's
    shape, dtype, and bytes. Deterministic across processes (tree_flatten
    order is canonical), so a restore can recompute and compare.

    Returns None when any leaf is not fully addressable — a multi-host
    shard can't be hashed locally, so those checkpoints carry no digest
    (restore still works; it just skips verification).
    """
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    digest = hashlib.sha256()
    digest.update(repr(treedef).encode("utf-8"))
    for leaf in leaves:
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return None
        array = np.asarray(leaf)
        digest.update(str(array.shape).encode("utf-8"))
        digest.update(str(array.dtype).encode("utf-8"))
        digest.update(array.tobytes())
    return digest.hexdigest()


def _metadata_path(directory, step):
    return storage.join(_normalize(directory), str(step) + METADATA_SUFFIX)


def _write_metadata(directory, step, digest, data_state):
    """Atomically writes the `<step>.meta.json` sidecar.

    Local writes go through a temp file + `os.replace` so a crash
    mid-write can never leave a half-written sidecar that a later
    `load_metadata` would misparse; GCS object writes are atomic
    already.
    """
    record = {
        "format": "cloud_tpu.checkpoint.meta.v1",
        "step": int(step),
        "digest": digest,
        "data_state": data_state,
        "time": time.time(),
    }
    path = _metadata_path(directory, step)
    payload = (json.dumps(record, sort_keys=True) + "\n").encode("utf-8")
    if storage.is_gcs_path(path):
        storage.write_bytes(path, payload)
        return path
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)
    return path


def load_metadata(directory, step):
    """The sidecar metadata dict for `<directory>/<step>` (content
    digest + graftguard `data_state`), or None for checkpoints written
    before the sidecar existed (they restore fine, unverified)."""
    try:
        payload = storage.read_bytes(_metadata_path(directory, step))
    except (OSError, ValueError):
        return None
    try:
        return json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, ValueError):
        logger.warning("Unreadable checkpoint metadata for step %s "
                       "under %s; ignoring it.", step, directory)
        return None


def _chaos_notify(path, step):
    # graftchaos checkpoint-truncation hook: fires only when the chaos
    # module is already loaded with an installed plan (sys.modules.get
    # keeps the normal save path import-free).
    chaos = sys.modules.get("cloud_tpu.analysis.chaos")
    if chaos is not None:
        chaos.notify_checkpoint(path, step)


def save(directory, state, step=0, force=True, use_async=False,
         data_state=None):
    """Saves a pytree `state` under `<directory>/<step>`.

    use_async: Return as soon as the state is snapshotted (device
    arrays copied out); the serialization/write happens on a background
    thread so training continues during the I/O — the standard trade
    for large states on slow stores (gs://). Call
    `wait_until_finished()` before reading the checkpoint back or
    exiting the process.

    data_state: Optional resumable data-stream position (graftguard:
    `Trainer.current_data_state()`), stamped into the metadata sidecar
    alongside the content digest so a restore can re-base the shuffle
    stream mid-epoch.
    """
    path = storage.join(_normalize(directory), str(step))
    if use_async:
        checkpointer = _get_async_checkpointer()
        with _pending_lock:
            same_path_pending = path in _pending_paths
        if same_path_pending:
            # Two async saves racing to one path would interleave
            # writes in the same directory; draining first turns the
            # race into last-writer-wins (and `force=True` then
            # overwrites a COMPLETE checkpoint, not a torn one).
            checkpointer.wait_until_finished()
            with _pending_lock:
                _pending_paths.clear()
        snapshot = _host_snapshot(state)
        with _pending_lock:
            _pending_paths.add(path)
        checkpointer.save(path, snapshot, force=force)
        # The digest hashes the host snapshot — the exact bytes the
        # background thread is committing, not the live (donatable)
        # device arrays.
        _write_metadata(directory, step, tree_digest(snapshot), data_state)
        _chaos_notify(path, step)
        return path
    with _checkpointer() as checkpointer:
        checkpointer.save(path, state, force=force)
    _write_metadata(directory, step, tree_digest(state), data_state)
    _chaos_notify(path, step)
    return path


def latest_step(directory):
    """Largest step number checkpointed under `directory` (local or
    gs://), or None."""
    wait_until_finished()  # in-flight async saves must be visible
    steps = [int(name) for name in storage.listdir(_normalize(directory))
             if name.isdigit()]
    return max(steps) if steps else None


def restore(directory, target, step=None, verify=True):
    """Restores a pytree congruent with `target` from `<directory>/<step>`.

    Args:
        directory: Checkpoint root (local or gs://).
        target: A pytree of arrays (or ShapeDtypeStructs) matching the
            saved structure; its shardings are respected on restore.
        step: Step to restore; default latest.
        verify: Recompute the content digest and compare it against the
            metadata sidecar's (when one was recorded). A mismatch — or
            a deserialize failure inside orbax — raises the typed
            `resilience.CheckpointCorrupt` so graftguard can quarantine
            the step and fall back to the previous checkpoint, instead
            of surfacing a cryptic tensorstore error.
    """
    directory = _normalize(directory)
    wait_until_finished()  # never read a checkpoint mid-write
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(
                "No checkpoints found under {}.".format(directory))
    path = storage.join(directory, str(step))
    abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                      target)
    try:
        with _checkpointer() as checkpointer:
            restored = checkpointer.restore(path, abstract)
    except Exception as e:
        from cloud_tpu.training import resilience

        raise resilience.CheckpointCorrupt(
            "Checkpoint {} failed to deserialize ({}: {}).".format(
                path, type(e).__name__, e),
            path=path, step=step) from e
    if verify:
        meta = load_metadata(directory, step)
        expected = None if meta is None else meta.get("digest")
        if expected:
            actual = tree_digest(restored)
            if actual is not None and actual != expected:
                from cloud_tpu.training import resilience

                raise resilience.CheckpointCorrupt(
                    "Checkpoint {} failed its content digest "
                    "(expected {}..., got {}...).".format(
                        path, expected[:12], actual[:12]),
                    path=path, step=step)
    return restored


def quarantine(directory, step):
    """Moves `<directory>/<step>` (and its metadata sidecar) aside as
    `<step>.corrupt` so `latest_step` falls back to the previous
    checkpoint — graftguard's answer to `CheckpointCorrupt`.

    Local paths rename atomically; gs:// objects have no rename, so
    quarantine is skipped there with a warning (the operator must move
    the object out of the prefix by hand). Returns the quarantine path,
    or None when nothing was moved.
    """
    norm = _normalize(directory)
    src = storage.join(norm, str(step))
    if storage.is_gcs_path(norm):
        logger.warning(
            "Cannot quarantine %s: gs:// has no rename. Move the "
            "object aside manually so resume stops selecting it.", src)
        return None
    if not os.path.exists(src):
        return None
    dst = src + ".corrupt"
    suffix = 0
    while os.path.exists(dst):
        suffix += 1
        dst = "{}.corrupt{}".format(src, suffix)
    try:
        os.replace(src, dst)
    except OSError:
        logger.warning("Failed to quarantine %s.", src, exc_info=True)
        return None
    meta_src = src + METADATA_SUFFIX
    if os.path.exists(meta_src):
        try:
            os.replace(meta_src, dst + METADATA_SUFFIX)
        except OSError:
            pass
    logger.warning("Quarantined corrupt checkpoint %s -> %s.", src, dst)
    return dst
