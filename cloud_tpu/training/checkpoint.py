"""Checkpoint/restore of train state via orbax.

The reference delegates checkpointing to Keras SavedModel + callbacks on
GCS, with a decoy-directory workaround so non-chief MWMS workers don't
corrupt the real save (reference cloud_fit/remote.py:130-145). Orbax's
single-writer protocol replaces that workaround; the per-step directory
layout (`<dir>/<step>`) keeps the tuner's per-trial checkpoint convention
(reference tuner/tuner.py:601-605).
"""

import os

import jax
import orbax.checkpoint as ocp


def _checkpointer():
    return ocp.StandardCheckpointer()


def save(directory, state, step=0, force=True):
    """Saves a pytree `state` under `<directory>/<step>`."""
    directory = os.path.abspath(directory)
    path = os.path.join(directory, str(step))
    with _checkpointer() as checkpointer:
        checkpointer.save(path, state, force=force)
    return path


def latest_step(directory):
    """Largest step number checkpointed under `directory`, or None."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = [int(name) for name in os.listdir(directory)
             if name.isdigit()]
    return max(steps) if steps else None


def restore(directory, target, step=None):
    """Restores a pytree congruent with `target` from `<directory>/<step>`.

    Args:
        directory: Checkpoint root.
        target: A pytree of arrays (or ShapeDtypeStructs) matching the
            saved structure; its shardings are respected on restore.
        step: Step to restore; default latest.
    """
    directory = os.path.abspath(directory)
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(
                "No checkpoints found under {}.".format(directory))
    path = os.path.join(directory, str(step))
    abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                      target)
    with _checkpointer() as checkpointer:
        return checkpointer.restore(path, abstract)
