"""Checkpoint/restore of train state via orbax.

The reference delegates checkpointing to Keras SavedModel + callbacks on
GCS, with a decoy-directory workaround so non-chief MWMS workers don't
corrupt the real save (reference cloud_fit/remote.py:130-145). Orbax's
single-writer protocol replaces that workaround; the per-step directory
layout (`<dir>/<step>`) keeps the tuner's per-trial checkpoint convention
(reference tuner/tuner.py:601-605).
"""

import os
import threading

import jax
import orbax.checkpoint as ocp

from cloud_tpu.utils import storage


def _checkpointer():
    return ocp.StandardCheckpointer()


_async_checkpointer = None
# In-flight async save bookkeeping: orbax already serializes saves
# through the single AsyncCheckpointer, but it does NOT guard two
# logical saves racing to the SAME <dir>/<step> path (a preemption
# re-save, a callback firing twice) — the second would start committing
# over the first's partially-written directory. The guard makes that a
# wait-then-write, and gives tests/Trainer an introspection point
# (`pending_saves()`), so a crash window can never leave a torn
# checkpoint that a later `latest_step` would pick up.
_pending_lock = threading.Lock()
_pending_paths = set()


def _get_async_checkpointer():
    # One process-wide AsyncCheckpointer: it owns the background write
    # thread, and orbax serializes saves through it (a second save waits
    # for the first), so per-save construction would forfeit the async.
    global _async_checkpointer
    if _async_checkpointer is None:
        _async_checkpointer = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())
    return _async_checkpointer


def wait_until_finished():
    """Blocks until every async save has committed. No-op when none are
    pending. Call before reading a checkpoint written with
    `save(..., use_async=True)` or at end of training. (Trainer.fit
    calls this on every exit path — normal return, EarlyStopping abort,
    or a raising train step — so fit never returns with a write still
    in flight.)"""
    if _async_checkpointer is not None:
        _async_checkpointer.wait_until_finished()
    with _pending_lock:
        _pending_paths.clear()


def pending_saves():
    """Snapshot of `<dir>/<step>` paths with an async save in flight
    (empty after wait_until_finished)."""
    with _pending_lock:
        return frozenset(_pending_paths)


def _host_snapshot(state):
    """Donation-safe copy of `state` for a background write.

    The train step donates its state buffers (`donate_argnums=0`):
    letting orbax serialize the LIVE device arrays while the next step
    runs would race the donation — the step could rewrite (or
    invalidate) the very buffers the writer thread is reading, tearing
    the checkpoint. One instrumented coalesced device_get pins the
    bytes on the host first; the write then proceeds from memory no
    future step can touch. Only fully-addressable trees snapshot —
    multi-host shardings keep the device arrays so orbax's distributed
    serialization protocol (which coordinates its own copy) still
    applies.
    """
    from cloud_tpu.parallel import runtime

    leaves = [l for l in jax.tree_util.tree_leaves(state)
              if isinstance(l, jax.Array)]
    if leaves and all(l.is_fully_addressable for l in leaves):
        # Phase label for the graftsan sanitizer: this coalesced fetch
        # is the sanctioned snapshot copy, whatever thread saves from.
        from cloud_tpu.monitoring import spans

        previous = runtime.set_phase("checkpoint")
        try:
            # graftscope: the snapshot copy is its own span so the
            # step-time breakdown can separate checkpoint stalls from
            # ordinary boundary fetches.
            with spans.span("checkpoint_snapshot"):
                return runtime.device_fetch(state)
        finally:
            runtime.set_phase(previous)
    return state


def _normalize(directory):
    """Local paths become absolute (orbax requires it); gs:// URIs pass
    through untouched — tensorstore reads/writes them directly."""
    if storage.is_gcs_path(directory):
        return str(directory).rstrip("/")
    return os.path.abspath(directory)


def save(directory, state, step=0, force=True, use_async=False):
    """Saves a pytree `state` under `<directory>/<step>`.

    use_async: Return as soon as the state is snapshotted (device
    arrays copied out); the serialization/write happens on a background
    thread so training continues during the I/O — the standard trade
    for large states on slow stores (gs://). Call
    `wait_until_finished()` before reading the checkpoint back or
    exiting the process.
    """
    path = storage.join(_normalize(directory), str(step))
    if use_async:
        checkpointer = _get_async_checkpointer()
        with _pending_lock:
            same_path_pending = path in _pending_paths
        if same_path_pending:
            # Two async saves racing to one path would interleave
            # writes in the same directory; draining first turns the
            # race into last-writer-wins (and `force=True` then
            # overwrites a COMPLETE checkpoint, not a torn one).
            checkpointer.wait_until_finished()
            with _pending_lock:
                _pending_paths.clear()
        snapshot = _host_snapshot(state)
        with _pending_lock:
            _pending_paths.add(path)
        checkpointer.save(path, snapshot, force=force)
        return path
    with _checkpointer() as checkpointer:
        checkpointer.save(path, state, force=force)
    return path


def latest_step(directory):
    """Largest step number checkpointed under `directory` (local or
    gs://), or None."""
    wait_until_finished()  # in-flight async saves must be visible
    steps = [int(name) for name in storage.listdir(_normalize(directory))
             if name.isdigit()]
    return max(steps) if steps else None


def restore(directory, target, step=None):
    """Restores a pytree congruent with `target` from `<directory>/<step>`.

    Args:
        directory: Checkpoint root (local or gs://).
        target: A pytree of arrays (or ShapeDtypeStructs) matching the
            saved structure; its shardings are respected on restore.
        step: Step to restore; default latest.
    """
    directory = _normalize(directory)
    wait_until_finished()  # never read a checkpoint mid-write
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(
                "No checkpoints found under {}.".format(directory))
    path = storage.join(directory, str(step))
    abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                      target)
    with _checkpointer() as checkpointer:
        return checkpointer.restore(path, abstract)
