"""Input pipeline: batched, shuffled, host-sharded iteration.

The reference delegates input pipelines to `tf.data` and per-worker
auto-sharding inside `tf.distribute` (reference cloud_fit/client.py:151-189
ships datasets as serialized tf.functions). The TPU-native pipeline is a
small, dependency-free design: numpy-backed batching on the host, static
shapes for XLA (tail batch dropped or padded), and per-process sharding
for multi-host pods. Overlap of host batching with device compute comes
from JAX async dispatch: the Trainer never blocks on device values inside
the step loop, so batch i+1 is prepared while step i runs.
"""

import logging
import os

import numpy as np

import jax
import jax.numpy as jnp

from cloud_tpu.parallel import runtime as runtime_lib

logger = logging.getLogger("cloud_tpu")


def epoch_permutation(num_examples, seed, epoch):
    """The canonical per-epoch shuffle order, shared host/device.

    Both the host path (`ArrayDataset._epoch_order`) and the
    device-resident executable (`Trainer._make_resident_run`) draw their
    order from the same jax threefry stream:
    `permutation(fold_in(PRNGKey(seed), epoch), num_examples)`. threefry
    is bit-deterministic across backends, so `cache="device"` reproduces
    the host path's batches exactly at a fixed seed (pinned by
    tests/unit/test_resident_data.py). Computed on the CPU backend when
    one is available so host-side epoch prep never dispatches through
    the accelerator tunnel.
    """
    def _draw():
        key = jax.random.fold_in(jax.random.PRNGKey(seed), epoch)
        return np.asarray(jax.random.permutation(key, num_examples))

    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except (RuntimeError, ValueError):
        return _draw()
    with jax.default_device(cpu):
        return _draw()


class ArrayDataset:
    """In-memory dataset of (features, labels) arrays.

    Args:
        x: Array or pytree of arrays with a common leading dimension.
        y: Optional array of labels (kept separate so loss/metric code can
            treat batches as (x, y) tuples).
        batch_size: Global batch size (across all processes/devices).
        shuffle: Reshuffle each epoch.
        seed: Shuffle seed (kept per-epoch deterministic so every process
            draws the same permutation — required for multi-host sharding
            to stay aligned).
        drop_remainder: Drop the tail batch (True keeps shapes static for
            XLA; False pads the tail by wrapping to the start).
        sample_weight: Optional [num_examples] per-example weights
            (the Keras `fit(sample_weight=)` contract); when set,
            batches are (x, y, w) triples and the Trainer weights the
            loss/metrics accordingly.
    """

    def __init__(self, x, y=None, batch_size=32, shuffle=False, seed=0,
                 drop_remainder=True, sample_weight=None):
        self.x = x
        # Keras accepts plain-list labels; indexing below needs arrays.
        self.y = None if y is None else np.asarray(y)
        y = self.y
        leaves = jax.tree_util.tree_leaves(x)
        if not leaves:
            raise ValueError("Empty dataset.")
        self.num_examples = leaves[0].shape[0]
        if y is not None and y.shape[0] != self.num_examples:
            raise ValueError(
                "x has {} examples but y has {}.".format(
                    self.num_examples, y.shape[0]))
        if sample_weight is not None:
            sample_weight = np.asarray(sample_weight, np.float32)
            if sample_weight.shape != (self.num_examples,):
                raise ValueError(
                    "sample_weight must be [num_examples]={}; got "
                    "shape {}.".format((self.num_examples,),
                                       sample_weight.shape))
        self.sample_weight = sample_weight
        if batch_size <= 0:
            raise ValueError("batch_size must be positive.")
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self._epoch = 0

    @property
    def steps_per_epoch(self):
        if self.drop_remainder:
            return self.num_examples // self.batch_size
        return -(-self.num_examples // self.batch_size)

    def _epoch_order(self):
        if self.shuffle:
            # Shared doctrine with the device-resident path: same seed,
            # same epoch -> same permutation on every process and on
            # either side of the wire (see epoch_permutation).
            return epoch_permutation(self.num_examples, self.seed,
                                     self._epoch)
        return np.arange(self.num_examples)

    def __iter__(self):
        """Yields global (x, y) numpy batches for one epoch."""
        return self.iter_from(0)

    def iter_from(self, start_step):
        """Yields one epoch's global batches starting at batch index
        `start_step` — the mid-epoch resume entry point (graftguard).

        The permutation is the SAME one `__iter__` would draw for this
        epoch (the threefry perm depends only on seed and the epoch
        counter), re-based by skipping the first `start_step` batches,
        so a resumed run continues the interrupted epoch's exact batch
        sequence. Epoch-counter semantics match `__iter__`: the counter
        advances at the first `next()`, not at generator creation.
        """
        order = self._epoch_order()
        self._epoch += 1
        steps = self.steps_per_epoch
        for step in range(int(start_step), steps):
            idx = order[step * self.batch_size:(step + 1) * self.batch_size]
            if len(idx) < self.batch_size:
                # Pad the tail by tiling the epoch order (robust even when
                # the whole dataset is smaller than one batch).
                idx = np.concatenate(
                    [idx, np.resize(order, self.batch_size - len(idx))])
            xb = jax.tree_util.tree_map(lambda a: a[idx], self.x)
            if self.sample_weight is not None:
                yield xb, (None if self.y is None else self.y[idx]), \
                    self.sample_weight[idx]
            elif self.y is None:
                yield xb
            else:
                yield xb, self.y[idx]

    def process_local_view(self, process_index=None, process_count=None,
                           start_step=0):
        """Returns this process's shard of each global batch.

        Multi-host feeding: every process iterates the same global order
        (same seed) and takes its contiguous slice of each batch; the
        slices are reassembled into a global array by
        `cloud_tpu.parallel.sharding.make_global_batch`. `start_step`
        re-bases the epoch mid-stream (see `iter_from`) — every process
        skips the same prefix, so the shards stay aligned on resume.
        """
        process_index = (jax.process_index()
                         if process_index is None else process_index)
        process_count = (jax.process_count()
                         if process_count is None else process_count)
        if self.batch_size % process_count:
            raise ValueError(
                "batch_size={} is not divisible by process_count={}.".format(
                    self.batch_size, process_count))
        shard = self.batch_size // process_count
        lo, hi = process_index * shard, (process_index + 1) * shard

        def _slices():
            for batch in self.iter_from(start_step):
                yield jax.tree_util.tree_map(lambda a: a[lo:hi], batch)
        return _slices()


class _LeafCast:
    """Per-leaf transfer decision. A plain object (not a registered
    pytree node) so a specs tree stays congruent with the feature tree
    under tree_map."""

    __slots__ = ("mode", "lo", "scale")

    def __init__(self, mode, lo=None, scale=None):
        self.mode = mode  # "keep" | "bf16" | "uint8"
        self.lo = lo
        self.scale = scale


class InputCast:
    """A narrow-on-the-wire transfer policy for feature batches.

    The host narrows features before the H2D copy (`host_cast`); the
    jitted train step widens them back to float32 as its first op
    (`widen`), so the model always computes in its own dtype and only
    the wire pays the narrow format:

    - "bfloat16": float leaves cross as bf16 — 2x fewer bytes, ~3
      decimal digits of mantissa, parameterless (works on streams).
    - "uint8": float leaves cross as affine-quantized uint8 — 4x fewer
      bytes; lo/scale are computed once from the full arrays, so this
      policy needs an `ArrayDataset`. Data already on a 255-point grid
      (images) round-trips exactly.

    Integer/bool leaves are never touched. Build instances through
    `make_input_cast`.
    """

    def __init__(self, name, specs):
        self.name = name
        self._specs = specs

    @property
    def cache_key(self):
        """Hashable identity for jit-closure caches: `widen` is baked
        into the compiled step, so steps must be cached per-policy."""
        return (self.name,) + tuple(
            (s.mode, s.lo, s.scale)
            for s in jax.tree_util.tree_leaves(self._specs))

    def host_cast(self, x):
        """Narrows a host feature batch for the wire (numpy in/out)."""
        def leaf(a, spec):
            if spec.mode == "bf16":
                return np.asarray(a).astype(jnp.bfloat16)
            if spec.mode == "uint8":
                q = np.round(
                    (np.asarray(a, np.float32) - spec.lo) / spec.scale)
                return np.clip(q, 0, 255).astype(np.uint8)
            return a
        return jax.tree_util.tree_map(leaf, x, self._specs)

    def widen(self, x):
        """Inverse of `host_cast`, traceable inside the jitted step."""
        def leaf(a, spec):
            if spec.mode == "bf16":
                return a.astype(jnp.float32)
            if spec.mode == "uint8":
                return a.astype(jnp.float32) * spec.scale + spec.lo
            return a
        return jax.tree_util.tree_map(leaf, x, self._specs)

    def cast_nbytes(self, x):
        """Post-cast byte count of `x` (no materialization)."""
        def leaf(a, spec):
            if spec.mode == "bf16":
                return a.size * 2
            if spec.mode == "uint8":
                return int(a.size)
            return int(np.asarray(a).nbytes)
        return sum(jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(leaf, x, self._specs)))


def make_input_cast(policy, x):
    """Builds an `InputCast` for feature tree `x`.

    Args:
        policy: None/"none" (returns None), "bfloat16"/"bf16", "uint8",
            or an existing `InputCast` (passed through).
        x: The feature tree the policy will apply to — the full arrays
            for "uint8" (range calibration), any representative sample
            for "bfloat16".
    """
    if policy is None or policy == "none":
        return None
    if isinstance(policy, InputCast):
        return policy

    def _is_float(a):
        return np.issubdtype(np.asarray(a).dtype, np.floating)

    if policy in ("bfloat16", "bf16"):
        specs = jax.tree_util.tree_map(
            lambda a: _LeafCast("bf16" if _is_float(a)
                                and np.asarray(a).dtype.itemsize > 2
                                else "keep"), x)
        return InputCast("bfloat16", specs)
    if policy == "uint8":
        def spec(a):
            if not _is_float(a):
                return _LeafCast("keep")
            a = np.asarray(a)
            lo = float(a.min())
            hi = float(a.max())
            scale = (hi - lo) / 255.0 or 1.0
            return _LeafCast("uint8", lo=lo, scale=scale)
        return InputCast("uint8", jax.tree_util.tree_map(spec, x))
    raise ValueError(
        "Unknown input_cast {!r}; expected None, 'bfloat16' or "
        "'uint8'.".format(policy))


def _resident_hbm_budget():
    """Per-device byte budget for the resident upload.

    CLOUD_TPU_RESIDENT_HBM_BUDGET (bytes) overrides; otherwise 60% of
    the device's reported bytes_limit (leaving room for params, grads,
    moments and activations); None (no check) when the backend reports
    nothing, as the virtual-CPU test backend doesn't.
    """
    env = os.environ.get("CLOUD_TPU_RESIDENT_HBM_BUDGET")
    if env:
        try:
            return int(float(env))
        except ValueError:
            logger.warning("Ignoring malformed "
                           "CLOUD_TPU_RESIDENT_HBM_BUDGET=%r", env)
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:  # backend without memory introspection
        return None
    limit = stats.get("bytes_limit")
    return int(limit * 0.6) if limit else None


class DeviceResidentDataset:
    """An `ArrayDataset` uploaded to device HBM once.

    Steady-state training then does ZERO host->device data transfers:
    the Trainer's resident executable draws every batch in-graph from
    the uploaded arrays with a device-side per-epoch permutation
    (`epoch_permutation` doctrine) and `jnp.take` /
    `lax.dynamic_slice`. Construct through `build()`, which applies the
    HBM budget check and falls back (returns None, one-line warning)
    instead of raising; `__init__` raises on structural problems.

    Attributes:
        data: Device-resident feature tree shaped like the dataset's
            per-batch yields ((x, y, w), (x, y) or bare x) but with the
            full example dimension.
        sharding: Congruent tree of NamedShardings (None off-mesh):
            leaves divisible by the dp axis are sharded on examples,
            the rest replicated.
        policy: The `InputCast` applied on upload (features stay narrow
            in HBM; the resident step widens per batch), or None.
        upload_bytes: Host bytes moved by the one-time upload.
    """

    def __init__(self, dataset, input_cast=None, mesh=None):
        if not isinstance(dataset, ArrayDataset):
            raise TypeError(
                "DeviceResidentDataset needs an ArrayDataset (in-memory "
                "arrays); got {!r}.".format(type(dataset).__name__))
        if dataset.steps_per_epoch < 1:
            raise ValueError(
                "Dataset yields no full batch (num_examples={}, "
                "batch_size={}).".format(dataset.num_examples,
                                         dataset.batch_size))
        if (not dataset.drop_remainder
                and dataset.num_examples % dataset.batch_size):
            raise ValueError(
                "drop_remainder=False with a ragged tail pads batches on "
                "the host; the resident path cannot reproduce that "
                "in-graph.")
        # The live dataset, not a copy: the resident fit loop reads and
        # advances its `_epoch` counter so shuffled order stays in
        # lockstep with (and resumable by) the host path.
        self.source = dataset
        self.num_examples = dataset.num_examples
        self.batch_size = dataset.batch_size
        self.steps_per_epoch = dataset.steps_per_epoch
        self.shuffle = dataset.shuffle
        self.seed = dataset.seed
        self.policy = (input_cast if isinstance(input_cast, InputCast)
                       or input_cast is None
                       else make_input_cast(input_cast, dataset.x))

        x = dataset.x if self.policy is None else self.policy.host_cast(
            dataset.x)
        if dataset.sample_weight is not None:
            host = (x, dataset.y, dataset.sample_weight)
            self.kind = "xyw"
        elif dataset.y is None:
            host = x
            self.kind = "x"
        else:
            host = (x, dataset.y)
            self.kind = "xy"

        self.sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from cloud_tpu.parallel import sharding as sharding_lib

            dp = dict(mesh.shape).get(sharding_lib.DATA_AXIS, 1)

            def leaf_sharding(a):
                if dp > 1 and a.shape[0] % dp == 0:
                    return NamedSharding(mesh, P(sharding_lib.DATA_AXIS))
                return NamedSharding(mesh, P())

            self.sharding = jax.tree_util.tree_map(leaf_sharding, host)

        self.upload_bytes = runtime_lib.record_h2d(host)
        if self.sharding is None:
            self.data = jax.tree_util.tree_map(jax.device_put, host)
        elif jax.process_count() > 1:
            # Every process holds the full arrays (the ArrayDataset
            # multi-host contract: same global order everywhere), so
            # each can serve any addressable shard by plain indexing.
            self.data = jax.tree_util.tree_map(
                lambda a, s: jax.make_array_from_callback(
                    a.shape, s, lambda idx, a=a: a[idx]),
                host, self.sharding)
        else:
            self.data = jax.tree_util.tree_map(
                jax.device_put, host, self.sharding)

    @classmethod
    def build(cls, dataset, input_cast=None, mesh=None,
              budget_bytes=None):
        """Residency with graceful fallback.

        Returns a `DeviceResidentDataset`, or None after ONE warning
        line when the dataset can't live on device (not in-memory
        arrays, no full batch, host-padded ragged tail, or over the
        HBM budget) — the caller then streams from the host as usual.
        """
        def _fallback(why):
            logger.warning(
                "cache='device' unavailable (%s); streaming from "
                "host instead.", why)
            return None

        if not isinstance(dataset, ArrayDataset):
            return _fallback("needs in-memory arrays, got {}".format(
                type(dataset).__name__))
        if dataset.steps_per_epoch < 1:
            return _fallback("dataset smaller than one batch")
        if (not dataset.drop_remainder
                and dataset.num_examples % dataset.batch_size):
            return _fallback("ragged tail is host-padded")

        policy = (input_cast if isinstance(input_cast, InputCast)
                  or input_cast is None
                  else make_input_cast(input_cast, dataset.x))
        budget = (_resident_hbm_budget() if budget_bytes is None
                  else budget_bytes)
        if budget is not None:
            need = cls._per_device_bytes(dataset, policy, mesh)
            if need > budget:
                return _fallback(
                    "dataset needs {} bytes/device, budget {}".format(
                        need, budget))
        return cls(dataset, input_cast=policy, mesh=mesh)

    @staticmethod
    def _per_device_bytes(dataset, policy, mesh):
        """Worst-device resident footprint after the input cast."""
        dp = 1
        if mesh is not None:
            from cloud_tpu.parallel import sharding as sharding_lib

            dp = dict(mesh.shape).get(sharding_lib.DATA_AXIS, 1) or 1

        def nbytes(a, cast_bytes):
            a = np.asarray(a)
            per = cast_bytes if cast_bytes is not None else a.nbytes
            return per // dp if dp > 1 and a.shape[0] % dp == 0 else per

        total = 0
        if policy is not None:
            specs = policy._specs
            flat_x = jax.tree_util.tree_leaves(dataset.x)
            flat_s = jax.tree_util.tree_leaves(specs)
            for a, s in zip(flat_x, flat_s):
                a = np.asarray(a)
                if s.mode == "bf16":
                    per = a.size * 2
                elif s.mode == "uint8":
                    per = int(a.size)
                else:
                    per = None
                total += nbytes(a, per)
        else:
            for a in jax.tree_util.tree_leaves(dataset.x):
                total += nbytes(a, None)
        for extra in (dataset.y, dataset.sample_weight):
            if extra is not None:
                total += nbytes(extra, None)
        return total


def as_dataset(data, y=None, batch_size=32, **kwargs):
    """Coerces user input to a re-iterable dataset of batches.

    Accepts (in resolution order):
    - an `ArrayDataset` (used as-is);
    - raw arrays or an array pytree (dict, or list/tuple of arrays) —
      wrapped in an `ArrayDataset`; always the case when `y` is given;
    - a one-shot iterator/generator of batches — materialized into a list
      once so multi-epoch training sees every batch every epoch;
    - any other re-iterable of batches (used as-is, re-iterated per
      epoch).
    """
    if isinstance(data, ArrayDataset):
        return data
    if y is not None or hasattr(data, "shape") or isinstance(data, dict):
        return ArrayDataset(data, y, batch_size=batch_size, **kwargs)
    if isinstance(data, (list, tuple)):
        leaves = [e for e in data]
        if leaves and all(hasattr(e, "shape") for e in leaves):
            # Pytree-of-arrays (multi-input model), not a batch list.
            return ArrayDataset(data, y, batch_size=batch_size, **kwargs)
        return data
    if hasattr(data, "__next__"):
        return list(data)
    if hasattr(data, "__iter__"):
        return data
    return ArrayDataset(data, y, batch_size=batch_size, **kwargs)


class GeneratorDataset:
    """Streaming dataset from an iterator factory.

    For data too large for memory: `factory` must return a fresh
    iterator of batches (numpy arrays or (x, y) tuples, fixed shapes
    for XLA) each time it is called — once per epoch, plus once for the
    Trainer's build-time sample peek, so keep it side-effect free.
    `steps_per_epoch` bounds each epoch for non-terminating streams
    (Trainer.fit picks it up when its own steps_per_epoch is unset).

    cloud_fit ships this WITHOUT materializing the stream: a
    module-level `factory` travels as its dotted path plus
    `factory_kwargs` (JSON), and the remote worker rebuilds the dataset
    and pulls batches there (the JAX-native analogue of the reference
    shipping datasets as serialized tf.functions,
    reference cloud_fit/client.py:151-189).
    """

    def __init__(self, factory, steps_per_epoch=None,
                 factory_kwargs=None):
        if not callable(factory):
            raise TypeError("factory must be callable, got {!r}"
                            .format(type(factory)))
        self.factory = factory
        self.steps_per_epoch = steps_per_epoch
        self.factory_kwargs = dict(factory_kwargs or {})

    def __iter__(self):
        return iter(self.factory(**self.factory_kwargs))


class NpzShardDataset:
    """Batches from .npz shards already sitting on storage.

    The cloud_fit shard-manifest path: the client ships only the list
    of shard paths (JSON manifest); the worker streams each shard
    through the storage seam (local or gs://) per epoch — data that
    never fits one `np.asarray` crosses as references, not bytes.

    Each shard is an .npz with an `x` array (and optionally `y`),
    uniform across shards except possibly a short last shard. Batches
    of `batch_size` are cut per shard; a shard tail smaller than
    `batch_size` is dropped (static shapes for XLA) unless the shard
    yields no full batch at all, in which case it is yielded whole.
    """

    def __init__(self, shard_paths, batch_size=32):
        if not shard_paths:
            raise ValueError("shard_paths must be non-empty.")
        if batch_size <= 0:
            raise ValueError("batch_size must be positive.")
        self.shard_paths = [str(p) for p in shard_paths]
        self.batch_size = batch_size

    def __iter__(self):
        import io

        from cloud_tpu.utils import storage

        for path in self.shard_paths:
            arrays = np.load(io.BytesIO(storage.read_bytes(path)))
            x = arrays["x"]
            y = arrays["y"] if "y" in arrays.files else None
            n = x.shape[0]
            steps = n // self.batch_size
            if steps == 0:
                yield (x, y) if y is not None else x
                continue
            for i in range(steps):
                sl = slice(i * self.batch_size, (i + 1) * self.batch_size)
                if y is not None:
                    yield x[sl], y[sl]
                else:
                    yield x[sl]


class ThreadedDataset:
    """Pulls a wrapped dataset on a background thread through a bounded
    queue — the host-side complement of `prefetch_to_device`.

    Device prefetch overlaps the host->HBM copy with compute; this
    overlaps producing the batches themselves (augmentation, decoding,
    a slow generator) with training. Wrap any dataset/iterable whose
    per-batch host work is non-trivial:

        ds = ThreadedDataset(GeneratorDataset(factory), buffer_size=4)
        trainer.fit(ds, ...)

    Semantics: batch order is preserved; producer exceptions re-raise
    in the consumer; abandoning iteration mid-epoch (steps_per_epoch,
    early break) stops the producer thread promptly. `steps_per_epoch`
    and evaluate's exactness attributes are forwarded from the wrapped
    dataset.
    """

    _SENTINEL = object()

    def __init__(self, dataset, buffer_size=4):
        if buffer_size < 1:
            raise ValueError("buffer_size must be >= 1.")
        if hasattr(dataset, "__next__"):
            raise TypeError(
                "ThreadedDataset needs a re-iterable (multi-epoch "
                "training re-iterates per epoch; a one-shot iterator "
                "would be silently empty after epoch 1). Wrap the "
                "source in GeneratorDataset(factory) instead.")
        self.dataset = dataset
        self.buffer_size = buffer_size
        for attr in ("steps_per_epoch", "num_examples", "batch_size"):
            value = getattr(dataset, attr, None)
            if value is not None:
                setattr(self, attr, value)

    def __iter__(self):
        return self._threaded(self.dataset)

    def __getattr__(self, name):
        # Forward the multi-host protocol ONLY when the wrapped dataset
        # provides it: Trainer dispatches on hasattr(process_local_view),
        # and an unconditional method would make wrapping a plain
        # GeneratorDataset crash on pods instead of iterating normally.
        if name == "process_local_view" and hasattr(
                self.dataset, "process_local_view"):
            return lambda *a, **k: self._threaded(
                self.dataset.process_local_view(*a, **k))
        raise AttributeError(name)

    def _threaded(self, source):
        import queue as queue_lib
        import threading

        q = queue_lib.Queue(maxsize=self.buffer_size)
        stop = threading.Event()

        def _put(item):
            """put() that gives up when the consumer is gone."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue_lib.Full:
                    continue
            return False

        def producer():
            try:
                for item in source:
                    if not _put((None, item)):
                        return
                _put((None, self._SENTINEL))
            except BaseException as e:  # noqa: BLE001 - re-raised below
                _put((e, None))

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()
        try:
            while True:
                err, item = q.get()
                if err is not None:
                    raise err
                if item is self._SENTINEL:
                    return
                yield item
        finally:
            # Deterministic shutdown: signal, then join — an abandoned
            # epoch (steps_per_epoch break) must not leave a producer
            # racing the next epoch's thread over the inner dataset.
            stop.set()
            thread.join(timeout=5.0)


def prefetch_to_device(iterator, size=2, sharding=None, feed=None,
                       limit=None):
    """Wraps a host batch iterator with device read-ahead.

    JAX async dispatch already overlaps host batching with device
    compute; explicit prefetch additionally overlaps the host->HBM copy
    of batch i+1 with step i, which matters when batches are large
    (images) relative to step time.

    Composes with the async host loop (trainer async_logging): this
    side keeps the H2D wire full while the background metric reader
    drains D2H — neither direction ever blocks the step dispatch, and
    both are counted in `runtime.transfer_stats()` (record_h2d here,
    record_d2h at every fetch site).

    Args:
        iterator: Host batch iterable.
        size: Read-ahead depth — `size` batches are queued on device
            ahead of the one being consumed (so up to size+1 alive;
            size=0 feeds synchronously, the minimal-HBM mode).
        sharding: Optional sharding for the default device_put feed.
        feed: Optional callable replacing the default device_put (e.g.
            a mesh-aware Trainer feed); its return value is yielded.
        limit: Bound pulls from the iterator BEFORE reading ahead —
            for steps_per_epoch over unbounded streams.
    """
    import collections
    import itertools

    if feed is None:
        def feed(batch):
            runtime_lib.record_h2d(batch)
            if sharding is None:
                return jax.device_put(batch)
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sharding), batch)

    it = iter(iterator)
    if limit is not None:
        it = itertools.islice(it, limit)
    if size <= 0:
        for batch in it:
            yield feed(batch)
        return

    queue = collections.deque()
    try:
        for _ in range(size):
            queue.append(feed(next(it)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(feed(next(it)))
        except StopIteration:
            pass
        yield out
