from cloud_tpu.training.async_logs import (AsyncMetricReader, LazyLogs,
                                           MetricFuture)
from cloud_tpu.training.callbacks import (Callback, EarlyStopping,
                                          LambdaCallback, MetricsLogger,
                                          ModelCheckpoint,
                                          PreemptionCheckpoint,
                                          TensorBoard, TerminateOnNaN,
                                          read_metrics_log)
from cloud_tpu.training.data import (ArrayDataset, DeviceResidentDataset,
                                     GeneratorDataset, InputCast,
                                     NpzShardDataset, ThreadedDataset,
                                     epoch_permutation, make_input_cast,
                                     prefetch_to_device)
from cloud_tpu.training import schedules
from cloud_tpu.training.resilience import (AutoCheckpoint,
                                           CheckpointCorrupt, DataStall,
                                           NaNLoss, Preemption,
                                           TrainingFault, guard_stats,
                                           resilient_fit)
from cloud_tpu.training.trainer import (Trainer, TrainState,
                                        sparse_categorical_crossentropy)
