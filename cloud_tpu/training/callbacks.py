"""Training callbacks: checkpointing, early stopping, metric streaming.

The reference relies on Keras callbacks, injecting per-trial TensorBoard +
ModelCheckpoint instances (reference tuner/tuner.py:576-605) and reading
metrics back by parsing TensorBoard event files from GCS (reference
tuner/tuner.py:532-560 — fragile, keyed on the `epoch_` tag prefix). The
TPU-native design keeps the per-trial directory layout but streams metrics
over an explicit JSONL channel (SURVEY §7.4 item 6), which
`DistributingCloudTuner` reads back without event-file parsing.
"""

import functools
import json

import jax
import jax.numpy as jnp

# Sharding-preserving device copy of a pytree. Runs under jit so it
# stays a device-side buffer copy — host-side jnp.array(copy=True)
# would try to materialize the value locally, which fails for
# multi-host arrays with non-addressable shards (FSDP/ZeRO-sharded
# params on pods). ONE module-level jit wrapper so the compiled copy is
# cached per tree structure/shape, not recompiled per snapshot.
_device_copy = jax.jit(
    functools.partial(jax.tree_util.tree_map, jnp.copy))


class Callback:
    """Base callback (Keras-parity hook names)."""

    def set_trainer(self, trainer):
        self.trainer = trainer

    def on_train_begin(self):
        pass

    def on_epoch_begin(self, epoch):
        pass

    def on_epoch_end(self, epoch, logs):
        pass

    def on_train_end(self, history):
        pass


class LambdaCallback(Callback):
    """Ad-hoc hooks from callables (Keras parity)."""

    def __init__(self, on_train_begin=None, on_epoch_begin=None,
                 on_epoch_end=None, on_train_end=None):
        self._on_train_begin = on_train_begin
        self._on_epoch_begin = on_epoch_begin
        self._on_epoch_end = on_epoch_end
        self._on_train_end = on_train_end

    def on_train_begin(self):
        if self._on_train_begin:
            self._on_train_begin()

    def on_epoch_begin(self, epoch):
        if self._on_epoch_begin:
            self._on_epoch_begin(epoch)

    def on_epoch_end(self, epoch, logs):
        if self._on_epoch_end:
            self._on_epoch_end(epoch, logs)

    def on_train_end(self, history):
        if self._on_train_end:
            self._on_train_end(history)


def _resolve_mode(mode, monitor):
    if mode == "auto":
        # Single source of truth for the name->direction heuristic,
        # shared with the tuner's Objective inference.
        from cloud_tpu.tuner.hyperparameters import (
            default_objective_direction)
        return default_objective_direction(monitor)
    return mode


def _improved(value, best, mode, min_delta=0.0):
    """Shared monitored-metric comparison for EarlyStopping/ModelCheckpoint."""
    if best is None:
        return True
    if mode == "min":
        return value < best - min_delta
    return value > best + min_delta


class EarlyStopping(Callback):
    """Stops training when a monitored metric stops improving.

    restore_best_weights: Keras parity — keep a device-resident copy of
    the parameters AND the extra variable collections (e.g. BatchNorm
    statistics) from the best epoch and put them back into the train
    state when training ends (whether stopped early or the epoch budget
    ran out with a best epoch recorded). Costs one extra copy of those
    buffers in HBM while training runs.
    """

    def __init__(self, monitor="val_loss", patience=0, min_delta=0.0,
                 mode="auto", restore_best_weights=False):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.mode = _resolve_mode(mode, monitor)
        self.restore_best_weights = bool(restore_best_weights)
        self.best = None
        self.wait = 0
        self._best_state = None

    def _improved(self, value):
        return _improved(value, self.best, self.mode, self.min_delta)

    def on_train_begin(self):
        self.best = None
        self.wait = 0
        self._best_state = None

    def _snapshot_state(self):
        # A REAL copy: the live buffers are donated to the next step.
        # Params AND extra_vars (BatchNorm statistics etc.) — restoring
        # best weights against last-epoch BN stats would pair tensors
        # from different models.
        self._best_state = (_device_copy(self.trainer.state.params),
                            _device_copy(self.trainer.state.extra_vars))

    def on_epoch_end(self, epoch, logs):
        value = logs.get(self.monitor)
        if value is None:
            return
        if self._improved(value):
            self.best = value
            self.wait = 0
            if self.restore_best_weights:
                self._snapshot_state()
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.trainer.stop_training = True

    def on_train_end(self, history):
        if self.restore_best_weights and self._best_state is not None:
            from cloud_tpu.training.trainer import TrainState

            best_params, best_extra = self._best_state
            s = self.trainer.state
            self.trainer.state = TrainState(
                s.step, best_params, s.opt_state, s.rng, best_extra)
            self._best_state = None


class TerminateOnNaN(Callback):
    """Stops training when the epoch loss goes non-finite (Keras
    `TerminateOnNaN` parity, at epoch granularity — per-step host
    checks would reintroduce the device->host sync the async host loop
    exists to remove).

    This is the canonical "callback that actually needs the value":
    under `fit(async_logging=True)` reading `logs["loss"]` here
    resolves the epoch's one coalesced background fetch — the NaN
    check costs that single round trip per epoch and nothing more.

    rollback=True turns the stop into a typed `resilience.NaNLoss`
    fault instead: under graftguard (`fit(resume="auto")`) the run
    ROLLS BACK to the last finite checkpoint and resumes with a fresh
    data-order rng (same params, different batch sequence) rather than
    dying — outside graftguard the typed fault simply propagates to
    the caller.
    """

    def __init__(self, monitor="loss", rollback=False):
        import math

        self.monitor = monitor
        self.rollback = bool(rollback)
        self._isfinite = math.isfinite

    def on_epoch_end(self, epoch, logs):
        value = logs.get(self.monitor)
        if value is None:
            return
        if not self._isfinite(float(value)):
            import logging

            if self.rollback:
                from cloud_tpu.training import resilience

                logging.getLogger("cloud_tpu").warning(
                    "epoch %d: %s is %r — raising NaNLoss for "
                    "graftguard rollback.", epoch, self.monitor, value)
                raise resilience.NaNLoss(
                    "epoch {}: {} is {!r}".format(epoch, self.monitor,
                                                  value),
                    epoch=epoch, monitor=self.monitor,
                    value=float(value))
            logging.getLogger("cloud_tpu").warning(
                "epoch %d: %s is %r — terminating training.",
                epoch, self.monitor, value)
            self.trainer.stop_training = True


class ModelCheckpoint(Callback):
    """Saves the train state each epoch (reference tuner/tuner.py:576-579:
    per-trial Keras ModelCheckpoint with save_freq='epoch').

    Non-chief processes write nothing (the checkpoint module handles the
    multi-host write protocol; see reference remote.py:130-145's decoy-dir
    workaround, which orbax-style single-writer semantics replace).
    """

    def __init__(self, filepath, monitor=None, mode="auto", min_delta=0.0,
                 save_freq="epoch", use_async=False):
        from cloud_tpu.training import checkpoint as checkpoint_lib
        self._checkpoint_lib = checkpoint_lib
        self.filepath = filepath
        self.monitor = monitor
        self.mode = _resolve_mode(mode, monitor or "loss")
        self.min_delta = abs(min_delta)
        if save_freq != "epoch":
            raise ValueError("Only save_freq='epoch' is supported.")
        # use_async: the epoch's save snapshots the state and writes on
        # a background thread, so epoch N+1 trains during the I/O (the
        # standard trade for big states on gs://); on_train_end blocks
        # until the last write commits.
        self.use_async = bool(use_async)
        self.best = None

    def on_epoch_end(self, epoch, logs):
        if self.monitor is not None:
            value = logs.get(self.monitor)
            if value is None:
                return
            if not _improved(value, self.best, self.mode, self.min_delta):
                return
            self.best = value
        self._checkpoint_lib.save(self.filepath, self.trainer.state,
                                  step=int(self.trainer.state.step),
                                  use_async=self.use_async)

    def on_train_end(self, history):
        if self.use_async:
            self._checkpoint_lib.wait_until_finished()


class PreemptionCheckpoint(Callback):
    """Checkpoints and stops cleanly on a preemption signal.

    TPU VMs get an eviction notice as SIGTERM (maintenance events,
    spot/preemptible reclaims). Without a handler, the process dies
    mid-step and the epoch's work is lost. With this callback:

        trainer.fit(..., callbacks=(PreemptionCheckpoint(ckpt_dir),),
                    resume_from=ckpt_dir)

    the signal calls `Trainer.request_stop()` (a host-flag stop at the
    next step boundary — no interrupted collective), the partial epoch
    closes out through the normal epoch-end path, the state is saved
    here, and fit() returns normally; the restart picks the checkpoint
    up via `resume_from=`. The previous signal handler is chained and
    restored at train end.

    Multi-host note: every process must receive the signal (true for
    whole-slice TPU preemptions — the platform notifies each worker
    VM); a signal delivered to only one process would stop it alone
    and hang the others' collectives.
    """

    def __init__(self, filepath, signals=None):
        import signal as signal_lib

        self.filepath = filepath
        self.signals = (tuple(signals) if signals is not None
                        else (signal_lib.SIGTERM,))
        self._old_handlers = {}
        self.preempted = False
        self._saved_step = None

    def on_train_begin(self):
        import signal as signal_lib

        self.preempted = False
        self._saved_step = None
        self._old_handlers = {}

        def handler(signum, frame):
            self.preempted = True
            self.trainer.request_stop()
            # Chain a previous callable handler (e.g. an outer
            # harness's own SIGTERM bookkeeping) — but NOT
            # default_int_handler, whose "chain" is raising
            # KeyboardInterrupt mid-step, the abrupt unwind this
            # callback exists to replace.
            old = self._old_handlers.get(signum)
            if callable(old) and old is not signal_lib.default_int_handler:
                old(signum, frame)

        for sig in self.signals:
            try:
                self._old_handlers[sig] = signal_lib.signal(sig, handler)
            except (ValueError, OSError):
                # Non-main thread (e.g. a tuner driving fits from a
                # worker thread): signal handling is unavailable;
                # request_stop() can still be called directly.
                self._old_handlers.pop(sig, None)

    def _save(self):
        from cloud_tpu.training import checkpoint as checkpoint_lib

        step = int(self.trainer.state.step)
        checkpoint_lib.save(self.filepath, self.trainer.state, step=step)
        self._saved_step = step

    def on_epoch_end(self, epoch, logs):
        if self.preempted:
            self._save()

    def on_train_end(self, history):
        import signal as signal_lib

        # The signal can land AFTER the final on_epoch_end ran (or in a
        # zero-step aborted epoch that skips epoch-end entirely): a
        # preemption must never exit without a checkpoint at the
        # current step.
        if (self.preempted and self.trainer.state is not None
                and self._saved_step != int(self.trainer.state.step)):
            self._save()
        for sig, old in self._old_handlers.items():
            try:
                signal_lib.signal(sig, old)
            except (ValueError, OSError, TypeError):
                pass
        self._old_handlers = {}


class MetricsLogger(Callback):
    """Streams per-epoch logs to a JSONL file — the metric return channel
    read back by DistributingCloudTuner (replacing event-file parsing,
    reference tuner/tuner.py:532-560).

    Local and `gs://` paths both work; each epoch appends one record
    (GCS objects are extended via compose — linear bytes over a run,
    however long)."""

    def __init__(self, path):
        self.path = path

    def on_train_begin(self):
        from cloud_tpu.utils import storage

        if jax.process_index() != 0:
            return
        # Truncate any previous run's stream.
        storage.write_bytes(self.path, b"")

    def on_epoch_end(self, epoch, logs):
        from cloud_tpu.utils import storage

        if jax.process_index() != 0:
            return
        record = {"epoch": epoch}
        record.update({k: float(v) for k, v in logs.items()})
        storage.append_bytes(self.path,
                             (json.dumps(record) + "\n").encode("utf-8"))


class TensorBoard(Callback):
    """Writes per-epoch scalars as real TensorBoard event files.

    Event-file COMPAT next to the primary JSONL channel (MetricsLogger):
    the reference's whole metric readback rides TensorBoard event files
    on GCS (reference tuner/tuner.py:532-560, tf_utils.py:27-51), and
    any TensorBoard pointed at `log_dir` renders these curves. The wire
    formats are hand-encoded in `utils.events` — no TensorFlow
    dependency. Chief-only writes, like every output channel here.
    """

    def __init__(self, log_dir):
        self.log_dir = log_dir
        self._writer = None

    def on_train_begin(self):
        from cloud_tpu.utils import events

        if jax.process_index() != 0:
            return
        self._writer = events.EventFileWriter(self.log_dir)

    def on_epoch_end(self, epoch, logs):
        if self._writer is None:
            return
        self._writer.add_scalars(
            epoch, {"epoch_" + k: float(v) for k, v in logs.items()})
        self._writer.flush()

    def on_train_end(self, history):
        if self._writer is not None:
            self._writer.close()


def read_metrics_log(path):
    """Parses a MetricsLogger JSONL stream into a list of epoch records."""
    from cloud_tpu.utils import storage

    records = []
    for line in storage.read_bytes(path).decode("utf-8").splitlines():
        line = line.strip()
        if line:
            records.append(json.loads(line))
    return records
