"""Trainer: a `model.fit`-style training loop, TPU-native.

The reference's training loop lives inside Keras under an ambient
`tf.distribute` strategy (reference core/preprocess.py:148-149,
cloud_fit/remote.py:84-128). This Trainer is the JAX equivalent: one
jitted train step over the ambient device mesh, parameters laid out by
explicit sharding rules (replicated for pure DP; XLA inserts the gradient
psum over ICI), batches sharded on the "dp" axis, buffers donated so the
optimizer update is in-place in HBM.

Works with any flax.linen Module, or any (init_fn, apply_fn) pair.

Example:
    trainer = Trainer(model=MLP(), optimizer=optax.adam(1e-3),
                      loss="sparse_categorical_crossentropy",
                      metrics=("accuracy",))
    history = trainer.fit(x_train, y_train, epochs=2, batch_size=128)
"""

import functools
import inspect
import itertools
import logging
import os
import sys
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax

from jax.sharding import NamedSharding, PartitionSpec as P

from cloud_tpu.monitoring import spans as spans_lib
from cloud_tpu.monitoring import watch as watch_lib
from cloud_tpu.parallel import runtime
from cloud_tpu.parallel import sharding as sharding_lib
from cloud_tpu.training import async_logs as async_logs_lib
from cloud_tpu.training import data as data_lib

logger = logging.getLogger("cloud_tpu")


def _env_sanitized(method):
    """Runs a Trainer entry point under a graftsan env scope.

    `CLOUD_TPU_SANITIZE=1|warn|strict` turns the wrapped call into a
    sanitized region (cloud_tpu.analysis.sanitizer): runtime transfer/
    compile records and jax.random key consumption are attributed to
    their call sites and checked against the step-loop invariants.
    Unset, the wrapper is a plain delegation — no import, no observer
    hook. Nested regions don't stack: a validation `evaluate` inside a
    sanitized `fit` sees the already-installed observer and no-ops.
    """
    @functools.wraps(method)
    def wrapper(self, *args, **kwargs):
        if not os.environ.get("CLOUD_TPU_SANITIZE"):
            return method(self, *args, **kwargs)
        from cloud_tpu.analysis import sanitizer
        with sanitizer.env_scope():
            return method(self, *args, **kwargs)
    return wrapper


def _env_telemetry(method):
    """Runs a Trainer entry point under a graftscope telemetry scope.

    `CLOUD_TPU_TELEMETRY=1` enables the ambient telemetry session
    (span tracer + metrics registry + exporters, see
    cloud_tpu.monitoring.telemetry) and guarantees a completed flush
    when the entry point returns, so trace.json / metrics.prom exist
    the moment fit() does. Unset, the wrapper is a plain delegation —
    no import, no tracer, no observer hook (the graftsan zero-cost
    discipline). Stacks with `_env_sanitized`: both observers ride the
    widened runtime fanout seam.
    """
    @functools.wraps(method)
    def wrapper(self, *args, **kwargs):
        if not os.environ.get("CLOUD_TPU_TELEMETRY"):
            return method(self, *args, **kwargs)
        from cloud_tpu.monitoring import telemetry
        with telemetry.env_scope():
            return method(self, *args, **kwargs)
    return wrapper


def _env_watched(method):
    """Runs a Trainer entry point under a graftwatch watchdog scope.

    `CLOUD_TPU_WATCH=1` installs the heartbeat watchdog
    (cloud_tpu.monitoring.watch): the step loop beats it, a monitor
    thread converts a stall past CLOUD_TPU_WATCH_DEADLINE into a typed
    `runtime.BackendUnavailable` plus a `blackbox.json` flight
    recorder, and liveness gauges ride the telemetry registry when one
    is active. Unset, the wrapper is a plain delegation — no import,
    no thread, no hook (the graftsan zero-cost discipline, test-
    pinned). Stacked OUTERMOST so a stall inside the telemetry scope
    still flushes artifacts on the way out, and so the crash blackbox
    sees the sanitizer/telemetry state before their teardown. A nested
    entry point (fit's validation evaluate) rides the outer watchdog.
    """
    @functools.wraps(method)
    def wrapper(self, *args, **kwargs):
        if not os.environ.get("CLOUD_TPU_WATCH"):
            return method(self, *args, **kwargs)
        from cloud_tpu.monitoring import watch
        with watch.env_scope():
            return method(self, *args, **kwargs)
    return wrapper


# -- Losses (logits-in, per-example-loss-out) ---------------------------

def _sparse_categorical_crossentropy(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def _categorical_crossentropy(logits, labels):
    return optax.softmax_cross_entropy(logits, labels)


def _binary_crossentropy(logits, labels):
    return optax.sigmoid_binary_cross_entropy(logits, labels)


def _mse(preds, targets):
    return jnp.mean(jnp.square(preds - targets),
                    axis=tuple(range(1, preds.ndim)))


def sparse_categorical_crossentropy(label_smoothing=0.0):
    """Loss factory: integer-label softmax CE with label smoothing.

    smoothing=0 is the registry default; >0 mixes the one-hot target
    with the uniform distribution (Keras `label_smoothing=` parity).
    """
    if not 0.0 <= label_smoothing < 1.0:
        raise ValueError("label_smoothing must be in [0, 1); got "
                         "{}.".format(label_smoothing))
    if not label_smoothing:
        return _sparse_categorical_crossentropy

    def loss(logits, labels):
        num_classes = logits.shape[-1]
        smoothed = optax.smooth_labels(
            jax.nn.one_hot(labels, num_classes), label_smoothing)
        return optax.softmax_cross_entropy(logits, smoothed)

    return loss


LOSSES = {
    "sparse_categorical_crossentropy": _sparse_categorical_crossentropy,
    "categorical_crossentropy": _categorical_crossentropy,
    "binary_crossentropy": _binary_crossentropy,
    "mse": _mse,
    "mean_squared_error": _mse,
}


def _accuracy(outputs, labels):
    """Per-example correctness (float). Mean-reduced by the train step;
    kept per-example so evaluate() can mask padded tail examples for
    exact example-weighted metrics."""
    preds = jnp.argmax(outputs, axis=-1)
    if labels.ndim == preds.ndim + 1:  # one-hot
        labels = jnp.argmax(labels, axis=-1)
    return (preds == labels).astype(jnp.float32)


def _top5_accuracy(outputs, labels):
    """Per-example top-5 hit rate (ImageNet's second headline metric).

    k clamps to the class count (Keras TopKCategoricalAccuracy
    behavior: fewer than 5 classes means every example hits)."""
    k = min(5, outputs.shape[-1])
    topk = jax.lax.top_k(outputs, k)[1]            # [B..., k]
    return jnp.any(topk == labels[..., None],
                   axis=-1).astype(jnp.float32)


def _mae_metric(outputs, labels):
    v = jnp.abs(outputs - labels)
    return v.reshape(v.shape[0], -1).mean(axis=1)


def _mse_metric(outputs, labels):
    v = jnp.square(outputs - labels)
    return v.reshape(v.shape[0], -1).mean(axis=1)


METRICS = {
    "accuracy": _accuracy,
    "top5_accuracy": _top5_accuracy,
    "mae": _mae_metric,
    "mean_absolute_error": _mae_metric,
    "mse": _mse_metric,
    "mean_squared_error": _mse_metric,
}

OPTIMIZERS = {
    "adam": lambda: optax.adam(1e-3),
    "adamw": lambda: optax.adamw(1e-3),
    "sgd": lambda: optax.sgd(1e-2, momentum=0.9),
    "rmsprop": lambda: optax.rmsprop(1e-3),
    "adagrad": lambda: optax.adagrad(1e-2),
    "adafactor": lambda: optax.adafactor(),  # the TPU LLM workhorse
    "lamb": lambda: optax.lamb(1e-3),
    "lion": lambda: optax.lion(1e-4),
}


def _per_example_view(v, batch_dim):
    """Collapse any non-batch dims (e.g. per-token losses) to one value
    per example so a per-example mask/weight applies cleanly."""
    v = jnp.asarray(v)
    if v.ndim > 1:
        return jnp.mean(v.reshape(batch_dim, -1), axis=1)
    return v


def _weighted_mean(v, weights):
    """sum(v*w) / sum(w), safe on all-zero weights.

    The tiny (1e-9, not 1.0) floor keeps the identity
    `weighted_mean * sum(w) == sum(v*w)` exact for ANY positive weight
    sum — evaluate() re-multiplies by sum(w) when aggregating across
    batches, so a 1.0 floor would silently scale batches whose total
    weight is below one. All-zero weights give 0, not nan.
    """
    return jnp.sum(v * weights) / jnp.maximum(jnp.sum(weights), 1e-9)


def _lead_count(batch):
    """The batch's leading (example) dimension, from its first shaped
    leaf — the host-side example count feeding and grouping key on."""
    lead = next((l for l in jax.tree_util.tree_leaves(batch)
                 if getattr(l, "shape", ())), None)
    return int(lead.shape[0]) if lead is not None else 0


def _emit_runtime_metrics(steps, examples, elapsed_secs):
    """Feeds the native metrics registry and ensures the periodic C++
    exporter is running (it refuses unless CLOUD_TPU_MONITORING_ENABLED
    is set) — once per epoch, off the hot loop."""
    if steps <= 0:
        return
    try:
        from cloud_tpu import monitoring
        monitoring.start_exporter()  # idempotent, env-gated
        monitoring.counter_increment(monitoring.TRAINING_STEPS, steps)
        monitoring.counter_increment(monitoring.TRAINING_EXAMPLES,
                                     examples)
        monitoring.histogram_observe(
            monitoring.STEP_TIME_HISTOGRAM,
            elapsed_secs / steps * 1e6,
            monitoring.STEP_TIME_BOUNDS)
    except Exception:  # monitoring must never break training
        logger.debug("metric emission failed", exc_info=True)


def _emit_telemetry_epoch(steps, examples, elapsed_secs):
    """Feeds the graftscope registry's per-epoch rollup (throughput
    counters + MFU gauge + one non-blocking flush). `sys.modules.get`
    keeps the disabled path import-free: if telemetry was never
    imported, it is certainly not enabled."""
    telemetry = sys.modules.get("cloud_tpu.monitoring.telemetry")
    if telemetry is None:
        return
    tele = telemetry.get()
    if tele is None or not tele.active:
        return
    try:
        tele.record_epoch(steps, examples, elapsed_secs)
    except Exception:  # telemetry must never break training
        logger.debug("telemetry epoch rollup failed", exc_info=True)


import typing


class ParamEmaState(typing.NamedTuple):
    """EMA shadow of the parameters.

    A DISTINCT node type (not a bare params-shaped subtree) so
    Trainer.build can recognize it structurally and keep the shadow in
    the PARAMETER layout — eval/predict substitute it straight into the
    params slot, so it must not pick up the ZeRO moment layout.
    """
    ema: typing.Any


def _trainable_labels(params, trainable):
    """"train"/"freeze" label per param leaf.

    trainable: regex (re.search over the same path strings
    param_sharding_rules match, e.g. "block_0/attention/query/kernel")
    or callable path_string -> bool.
    """
    import re

    if callable(trainable):
        matches = trainable
    else:
        pattern = re.compile(trainable)
        matches = lambda path: pattern.search(path) is not None
    return jax.tree_util.tree_map_with_path(
        lambda path, _: ("train"
                         if matches(sharding_lib.path_string(path))
                         else "freeze"),
        params)


def _freeze_untrainable(optimizer, trainable):
    """Wraps an optimizer so only `trainable`-matched params update.

    Frozen leaves get `optax.set_to_zero`, and `optax.multi_transform`'s
    masking means the wrapped optimizer allocates state (Adam moments
    etc.) ONLY for the trainable subset — frozen positions hold
    `optax.MaskedNode` placeholders (see build()'s masked-moment
    sharding).
    """
    return optax.multi_transform(
        {"train": optimizer, "freeze": optax.set_to_zero()},
        lambda params: _trainable_labels(params, trainable))


def _param_ema(decay):
    """optax transform tracking an EMA of the PARAMETERS.

    Chained AFTER the base optimizer: update() sees the pre-update
    params and the final updates, reconstructs the post-update params,
    and folds them into the shadow.
    """

    def init(params):
        # A REAL copy: jnp.asarray would alias the live param buffers,
        # and aliased leaves break the train step's state donation
        # (same buffer donated twice).
        return ParamEmaState(ema=jax.tree_util.tree_map(
            lambda p: jnp.array(p, copy=True), params))

    def update(updates, state, params=None):
        if params is None:
            raise ValueError("param_ema requires params in update().")
        new_params = optax.apply_updates(params, updates)
        ema = jax.tree_util.tree_map(
            lambda e, p: decay * e + (1.0 - decay) * p,
            state.ema, new_params)
        return updates, ParamEmaState(ema=ema)

    return optax.GradientTransformation(init, update)


class TrainState:
    """Step + params + optimizer state + auxiliary model variables
    (e.g. flax batch_stats), registered as a pytree."""

    def __init__(self, step, params, opt_state, rng, extra_vars=None):
        self.step = step
        self.params = params
        self.opt_state = opt_state
        self.rng = rng
        self.extra_vars = {} if extra_vars is None else extra_vars

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state, self.rng,
                self.extra_vars), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten)


class Trainer:
    """Keras-`model.fit` parity on a JAX device mesh."""

    def __init__(self,
                 model,
                 optimizer="adam",
                 loss="sparse_categorical_crossentropy",
                 metrics=("accuracy",),
                 mesh=None,
                 param_sharding_rules=None,
                 train_kwargs=None,
                 eval_kwargs=None,
                 rng_keys=(),
                 seed=0,
                 aux_loss_weight=0.01,
                 gradient_accumulation_steps=1,
                 remat=False,
                 zero1=False,
                 fsdp=False,
                 ema_decay=None,
                 steps_per_execution=1,
                 trainable=None):
        """Constructor.

        Args:
            model: A flax.linen Module (init/apply), or a tuple
                (init_fn, apply_fn) with init_fn(rng, x)->params and
                apply_fn(params, x, **kwargs)->outputs.
            optimizer: optax `GradientTransformation` or a name in
                OPTIMIZERS.
            loss: callable(outputs, labels)->per-example loss, or a name
                in LOSSES.
            metrics: iterable of names in METRICS or callables
                (outputs, labels)->scalar.
            mesh: Device mesh; defaults to the ambient runtime mesh (or
                single-device execution when neither exists).
            param_sharding_rules: list of (path_regex, PartitionSpec) for
                model-parallel layouts; default replicates params (DP).
            train_kwargs: extra kwargs passed to apply during training
                (e.g. {"train": True} or {"deterministic": False}).
            eval_kwargs: extra kwargs for evaluation/prediction.
            rng_keys: names of per-step rngs to pass to flax apply (e.g.
                ("dropout",)).
            seed: PRNG seed.
            aux_loss_weight: Weight on auxiliary losses the model sows
                into the "losses" collection (e.g. MoE load-balancing
                loss; Switch-Transformer default 0.01).
            gradient_accumulation_steps: Accumulate gradients over N
                steps before applying the update (`optax.MultiSteps`) —
                N small device batches emulate one N-x-larger global
                batch when HBM cannot hold it.
            remat: Rematerialize the forward pass in backward
                (`jax.checkpoint`): trades recompute FLOPs for
                activation memory — the standard lever for long
                sequences / deep models on HBM-bound chips.
            zero1: Shard optimizer state (Adam moments etc.) over the
                data axis — ZeRO stage 1. Optimizer memory drops to
                O(1/|dp|) per device for one all-gather of the updates
                per step; parameters keep their layout. No-op without a
                mesh or a >1-sized "dp" axis.
            fsdp: Fully-shard parameters themselves over the data axis
                (ZeRO-3 style), on top of any param_sharding_rules; XLA
                all-gathers weights at use and reduce-scatters grads.
                Implies the zero1 moment layout (moments follow their
                params). No-op without a mesh or a >1-sized "dp" axis.
            steps_per_execution: Run N optimizer steps per XLA
                executable call (Keras `steps_per_execution`): fit
                stacks N host batches and a `lax.scan` executes them in
                ONE dispatch — the host-overhead amortizer for
                fast steps and high-latency links (the tunneled chip
                pays ~66ms per dispatch, PERF.md). Works on multi-host
                pods (local groups assemble into global stacked
                arrays); leftover/ragged batches run through the
                single-step path.
            trainable: Optional param-path regex (or callable
                path_string -> bool): only matching parameters receive
                optimizer updates; the rest are frozen — the
                fine-tuning lever for imported checkpoints (e.g.
                `trainable=r"lm_head|block_11"` trains the head and
                last block of an `import_hf_llama` model). Matching
                uses `re.search` on the same "block_0/attention/query/
                kernel" path strings as `param_sharding_rules`. Frozen
                parameters allocate NO optimizer state (`optax.
                multi_transform` masking), so Adam moments shrink to
                the trainable subset.
            ema_decay: Track an exponential moving average of the
                parameters (e.g. 0.999): `ema_params` exposes the
                shadow, and evaluate/predict take `use_ema=True` to
                run on it — the standard eval-quality lever for vision
                and diffusion training. The shadow lives in optimizer
                state (checkpointed, sharded like the params).
        """
        if hasattr(model, "init") and hasattr(model, "apply"):
            self._init_fn = model.init
            self._apply_fn = model.apply
            self._is_flax = True
        else:
            self._init_fn, self._apply_fn = model
            self._is_flax = False
        self.model = model

        # Original constructor specs are kept for cross-process shipping
        # (cloud_fit serializes names/callables, not optax closures).
        self.optimizer_spec = optimizer
        self.loss_spec = loss
        self.metric_specs = tuple(metrics)

        if isinstance(optimizer, str):
            optimizer = OPTIMIZERS[optimizer]()
        self.trainable = trainable
        if trainable is not None:
            optimizer = _freeze_untrainable(optimizer, trainable)
        self.ema_decay = ema_decay
        if ema_decay is not None:
            if not 0.0 < ema_decay < 1.0:
                raise ValueError(
                    "ema_decay must be in (0, 1); got {}.".format(
                        ema_decay))
            # Chained before any MultiSteps wrap so the shadow folds in
            # applied updates (zero updates on accumulation micro-steps
            # just decay toward unchanged params — harmless smoothing).
            optimizer = optax.chain(optimizer, _param_ema(ema_decay))
        self.steps_per_execution = int(steps_per_execution)
        if self.steps_per_execution < 1:
            raise ValueError(
                "steps_per_execution must be >= 1; got {}.".format(
                    steps_per_execution))
        self.gradient_accumulation_steps = int(gradient_accumulation_steps)
        if self.gradient_accumulation_steps > 1:
            optimizer = optax.MultiSteps(
                optimizer, every_k_schedule=self.gradient_accumulation_steps)
        self.optimizer = optimizer
        self.remat = bool(remat)
        self.zero1 = bool(zero1)
        self.fsdp = bool(fsdp)

        if loss is sparse_categorical_crossentropy:
            # The FACTORY, not a loss: Keras muscle memory makes
            # `loss=sparse_categorical_crossentropy` an easy slip that
            # would otherwise fail with an arity error deep inside the
            # jitted step.
            raise TypeError(
                "sparse_categorical_crossentropy is a factory — call it "
                "(e.g. loss=sparse_categorical_crossentropy(0.1)) or "
                "use the string 'sparse_categorical_crossentropy'.")
        self.loss_fn = LOSSES[loss] if isinstance(loss, str) else loss
        self.metric_fns = {}
        for m in metrics:
            if isinstance(m, str):
                self.metric_fns[m] = METRICS[m]
            else:
                self.metric_fns[getattr(m, "__name__", "metric")] = m

        self._mesh = mesh if mesh is not None else runtime.global_mesh()
        self.param_sharding_rules = param_sharding_rules
        self.train_kwargs = dict(train_kwargs or {})
        self.eval_kwargs = dict(eval_kwargs or {})
        self.rng_keys = tuple(rng_keys)
        self.seed = seed
        self.aux_loss_weight = aux_loss_weight
        self._sows_losses = False  # set by build() when the model sows

        self.state = None
        self._jit_train_step = None
        self._jit_eval_step = None
        self._scalar_unmasked_metrics = set()
        self._jit_predict_step = None
        self.stop_training = False  # set by callbacks (EarlyStopping)
        # Step-granular abort (preemption): checked between steps in the
        # fit loop — a plain host bool, so the check costs nothing and
        # never syncs the device. request_stop() sets it.
        self._abort_epoch = False
        # graftguard state: the live data-stream position (stamped into
        # checkpoint metadata by AutoCheckpoint/rescue saves), the armed
        # resume-latency probe, and the active chaos plan.
        self._data_progress = None
        self._resume_probe = None
        self._chaos = None

    # -- state construction --------------------------------------------

    def _apply(self, params, x, extra_vars=None, rngs=None, mutable=False,
               **kwargs):
        if self._is_flax:
            variables = dict({"params": params}, **(extra_vars or {}))
            extra = {}
            if rngs:
                extra["rngs"] = rngs
            if mutable:
                extra["mutable"] = mutable
            return self._apply_fn(variables, x, **extra, **kwargs)
        return self._apply_fn(params, x, **kwargs)

    def build(self, sample_x, variables=None):
        """Initializes parameters/optimizer state (lazily called by fit).

        variables: optional pre-trained variables to build FROM —
        e.g. the dict `models.import_hf_llama`/`import_hf_gpt2`/
        `import_hf_deepseek` return — instead of random init (the
        fine-tuning entry point; the Keras analogue of building a
        model with loaded weights). Provided collections override the
        freshly initialized ones per collection ({"params": ...} alone
        keeps fresh batch_stats etc.); params must match the model's
        structure and shapes exactly, checked loudly. Optimizer state,
        shardings, and trainable= masking are derived from the
        provided weights like any other build.
        """
        if self.state is not None:
            if variables is not None:
                # Returning the existing (possibly random-init) state
                # while the caller believes a checkpoint was loaded is
                # the silent-divergence failure mode this API exists
                # to avoid.
                raise RuntimeError(
                    "build(variables=...) called on an already-built "
                    "Trainer: the provided weights would be ignored. "
                    "Load weights before the first fit/evaluate/"
                    "predict/build call.")
            return self.state
        rng = jax.random.PRNGKey(self.seed)
        init_rng, state_rng = jax.random.split(rng)
        sample = jax.tree_util.tree_map(
            lambda a: jnp.asarray(a[:1]), sample_x)
        init_kwargs = dict(self.train_kwargs)
        init_variables = self._init_fn(init_rng, sample, **init_kwargs)
        if variables is not None:
            if not (self._is_flax and "params" in init_variables):
                raise ValueError(
                    "build(variables=...) needs a flax model (the "
                    "(init_fn, apply_fn) path has no collections).")
            if "params" not in variables:
                raise ValueError(
                    "build(variables=...) must include a 'params' "
                    "collection (got {}).".format(sorted(variables)))
            init_shapes = jax.tree_util.tree_map(
                jnp.shape, init_variables["params"])
            try:
                given_shapes = jax.tree_util.tree_map(
                    jnp.shape, variables["params"])
                matches = init_shapes == given_shapes
            except ValueError:
                matches = False
            if not matches:
                raise ValueError(
                    "build(variables=...): provided params do not "
                    "match the model's structure/shapes — wrong "
                    "checkpoint for this model configuration?")
            init_variables = {**dict(init_variables), **dict(variables)}
        variables = init_variables
        if self._is_flax and "params" in variables:
            variables = dict(variables)
            params = variables.pop("params")
            # "losses" is a transient per-step collection (sown aux
            # losses, e.g. MoE load balancing), not persistent state.
            self._sows_losses = variables.pop("losses", None) is not None
            extra_vars = variables  # e.g. {"batch_stats": ...}
        else:
            params, extra_vars = variables, {}
        if self._mesh is not None:
            if self.fsdp:
                param_sharding = sharding_lib.fsdp_sharding(
                    params, self._mesh, rules=self.param_sharding_rules)
            else:
                param_sharding = sharding_lib.param_sharding(
                    params, self.param_sharding_rules, self._mesh)
            params = jax.tree_util.tree_map(
                lambda a, s: jax.device_put(a, s), params, param_sharding)
            # Optimizer-state layout: optax states embed params-shaped
            # subtrees (Adam moments) — those inherit the param sharding
            # (tp-sharded moments for tp-sharded params); everything else
            # (step counters) replicates. Structural substitution is used
            # because jnp.zeros_like in init has no data dependence on
            # params, so jit sharding propagation cannot infer this.
            abstract_opt = jax.eval_shape(self.optimizer.init, params)
            param_struct = jax.tree_util.tree_structure(params)
            # fsdp params are already dp-sharded, so moments inheriting
            # the param layout are ZeRO-sharded for free; zero1 adds the
            # dp moment layout without touching the params.
            moment_sharding = param_sharding
            if self.zero1 and not self.fsdp:
                moment_sharding = sharding_lib.zero1_opt_sharding(
                    params, param_sharding, self._mesh)

            # Trainable-subset masking (optax.multi_transform) swaps
            # frozen leaves for MaskedNode, so masked moments are NOT
            # params-shaped: recognize that structure too, or every
            # moment falls into the replicated fallback and the
            # zero1/fsdp/tp layouts silently vanish exactly for the
            # fine-tuning runs the feature targets.
            masked_struct = None
            if self.trainable is not None:
                labels = _trainable_labels(params, self.trainable)
                _mask_like = lambda tree: jax.tree_util.tree_map(
                    lambda lbl, leaf: (leaf if lbl == "train"
                                       else optax.MaskedNode()),
                    labels, tree)
                masked_struct = jax.tree_util.tree_structure(
                    _mask_like(params))
                masked_moment_sharding = _mask_like(moment_sharding)

            def _is_params_shaped(node):
                if isinstance(node, ParamEmaState):
                    return True
                struct = jax.tree_util.tree_structure(node)
                return (struct == param_struct
                        or (masked_struct is not None
                            and struct == masked_struct))

            def _subtree_sharding(node):
                if isinstance(node, ParamEmaState):
                    # The EMA shadow substitutes into the params slot at
                    # eval time, so it keeps the PARAM layout even under
                    # zero1 moment sharding.
                    return ParamEmaState(ema=param_sharding)
                if (masked_struct is not None
                        and jax.tree_util.tree_structure(node)
                        == masked_struct):
                    return masked_moment_sharding
                if _is_params_shaped(node):
                    return moment_sharding
                return jax.tree_util.tree_map(
                    lambda _: sharding_lib.replicated(self._mesh), node)

            opt_sharding = jax.tree_util.tree_map(
                _subtree_sharding, abstract_opt,
                is_leaf=_is_params_shaped)
            opt_state = runtime.instrumented_jit(
                self.optimizer.init, out_shardings=opt_sharding)(params)
            replicate_all = lambda tree: jax.tree_util.tree_map(
                lambda _: sharding_lib.replicated(self._mesh), tree)
            extra_vars = jax.tree_util.tree_map(
                lambda a: jax.device_put(
                    jnp.asarray(a), sharding_lib.replicated(self._mesh)),
                extra_vars)
            self._state_sharding = TrainState(
                sharding_lib.replicated(self._mesh),
                param_sharding,
                opt_sharding,
                sharding_lib.replicated(self._mesh),
                replicate_all(extra_vars))
            state = TrainState(
                jax.device_put(jnp.zeros((), jnp.int32),
                               sharding_lib.replicated(self._mesh)),
                params,
                opt_state,
                jax.device_put(state_rng,
                               sharding_lib.replicated(self._mesh)),
                extra_vars)
        else:
            opt_state = self.optimizer.init(params)
            self._state_sharding = None
            state = TrainState(jnp.zeros((), jnp.int32), params, opt_state,
                               state_rng, extra_vars)
        self.state = state
        return state

    # -- jitted steps ---------------------------------------------------

    @staticmethod
    def _batch_widener(policy, weighted):
        """In-graph inverse of the `input_cast` host narrowing: widens
        the features slot of a train batch back to float32 as the
        step's first op, so the model computes in its own dtype and
        only the wire (or resident HBM storage) pays the narrow
        format. None when no policy is active."""
        if policy is None:
            return None
        if weighted:
            def widen(batch):
                x, y, w = batch
                return (policy.widen(x), y, w)
        else:
            def widen(batch):
                x, y = batch
                return (policy.widen(x), y)
        return widen

    def _make_train_step_body(self, weighted=False, widen=None):
        """The raw (unjitted) train step closure — the single source of
        truth shared by the jitted single-step path, the
        steps_per_execution scan and the device-resident executable.

        weighted: batches are (x, y, sample_weight) triples — the
        loss is the weighted batch mean (Keras sum-over-batch-size
        semantics: mean(per_example * w)) and per-example metrics are
        weighted means (sum(v*w)/sum(w)).

        widen: optional in-graph batch transform (`_batch_widener`)
        restoring input_cast-narrowed features to float32."""
        metric_fns = self.metric_fns
        loss_fn = self.loss_fn
        optimizer = self.optimizer
        train_kwargs = self.train_kwargs
        train_mask_aware = {name: self._metric_accepts_mask(fn)
                            for name, fn in metric_fns.items()}
        rng_keys = self.rng_keys

        aux_loss_weight = self.aux_loss_weight
        sows_losses = self._sows_losses
        # Scalar metrics that can't take weights, recorded at trace
        # time (fit() checks after the first step on the weighted path).
        train_scalar_unmasked = self._train_scalar_unmasked = set()

        def train_step(state, batch):
            if widen is not None:
                batch = widen(batch)
            if weighted:
                x, y, w = batch
                w = w.astype(jnp.float32)
            else:
                x, y = batch
                w = None
            step_rng = jax.random.fold_in(state.rng, state.step)
            rngs = ({k: jax.random.fold_in(step_rng, i)
                     for i, k in enumerate(rng_keys)} or None)
            mutable = list(state.extra_vars.keys())
            if sows_losses:
                mutable = mutable + ["losses"]

            def compute_loss(params):
                if mutable:
                    outputs, new_vars = self._apply(
                        params, x, extra_vars=state.extra_vars, rngs=rngs,
                        mutable=mutable, **train_kwargs)
                else:
                    outputs = self._apply(params, x, rngs=rngs,
                                          **train_kwargs)
                    new_vars = state.extra_vars
                per_example = loss_fn(outputs, y)
                if w is not None:
                    # Weighted Keras semantics: collapse any non-batch
                    # dims per example, then mean(per_example * w)
                    # (sum-over-batch-size, NOT normalized by sum(w)).
                    per_example = _per_example_view(per_example,
                                                    w.shape[0]) * w
                loss = jnp.mean(per_example)
                new_vars = dict(new_vars)
                sown = new_vars.pop("losses", None)
                if sown is not None:
                    aux = sum(jnp.sum(jnp.asarray(l).astype(loss.dtype))
                              for l in jax.tree_util.tree_leaves(sown))
                    loss = loss + aux_loss_weight * aux
                return loss, (outputs, new_vars)

            if self.remat:
                # Recompute the forward in backward instead of keeping
                # activations: HBM for FLOPs.
                compute = jax.checkpoint(compute_loss)
            else:
                compute = compute_loss
            (loss, (outputs, new_vars)), grads = jax.value_and_grad(
                compute, has_aux=True)(state.params)
            if isinstance(optimizer, (optax.GradientTransformationExtraArgs,
                                      optax.MultiSteps)):
                # The extra-args protocol carries the step's loss to
                # loss-aware transforms (optax.contrib.reduce_on_plateau
                # chained after the base optimizer). In current optax
                # every built-in optimizer is ExtraArgs-typed and simply
                # ignores unknown extras, so this is the COMMON branch;
                # MultiSteps (grad accumulation) forwards **extra_args
                # to its inner chain. Only raw custom
                # GradientTransformations (e.g. _param_ema) take the
                # plain call below.
                updates, new_opt_state = optimizer.update(
                    grads, state.opt_state, state.params, value=loss)
            else:
                updates, new_opt_state = optimizer.update(
                    grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            new_state = TrainState(state.step + 1, new_params,
                                   new_opt_state, state.rng, new_vars)
            logs = {"loss": loss}
            for name, fn in metric_fns.items():
                # Mean-reduce: metric fns may return per-example values
                # (built-ins do) or a scalar; train logs are batch means
                # (weighted means under sample_weight). Mask-aware
                # metrics (fn(outputs, y, mask=...), the padded-eval
                # contract) get the weights as the mask — or all-ones,
                # train batches are never padded.
                lead = jax.tree_util.tree_leaves(outputs)[0].shape[0]
                mask = w if w is not None else jnp.ones((lead,),
                                                        jnp.float32)
                if train_mask_aware[name]:
                    # Same contract as eval: per-example returns get
                    # the weighted mean; scalars are already weighted.
                    v = jnp.asarray(fn(outputs, y, mask=mask))
                    if v.ndim >= 1:
                        logs[name] = _weighted_mean(
                            _per_example_view(v, lead), mask)
                    else:
                        logs[name] = v
                    continue
                v = jnp.asarray(fn(outputs, y))
                if v.ndim >= 1:
                    logs[name] = _weighted_mean(
                        _per_example_view(v, lead), mask)
                else:
                    # Scalar metric with no way to apply weights:
                    # recorded at trace time; fit() raises on the
                    # weighted path instead of logging an unweighted
                    # number (mirror of evaluate()'s guard).
                    if weighted:
                        train_scalar_unmasked.add(name)
                    logs[name] = jnp.mean(v)
            if weighted:
                # For exact epoch-level aggregation: per-batch weighted
                # means must be re-weighted by their batch weight sums
                # (a plain mean of ratios is biased when batch sums
                # differ). Stripped from user-facing logs in
                # _fit_epochs.
                logs["_batch_weight"] = jnp.sum(w)
            return new_state, logs

        return train_step

    def _make_train_step(self, weighted=False, widen=None):
        train_step = self._make_train_step_body(weighted=weighted,
                                                widen=widen)
        if self._mesh is None:
            return runtime.instrumented_jit(train_step, donate_argnums=0)
        batch_sharding = sharding_lib.batch_sharding(self._mesh)
        batch_in = ((batch_sharding,) * 3 if weighted
                    else (batch_sharding, batch_sharding))
        return runtime.instrumented_jit(
            train_step,
            in_shardings=(self._state_sharding, batch_in),
            out_shardings=(self._state_sharding, None),
            donate_argnums=0)

    @staticmethod
    def _reduce_scan_logs(logs_seq):
        """Group-level aggregation of scanned per-step logs ([num_steps]
        leaves) — shared by the steps_per_execution executable and the
        device-resident executable.

        Weighted groups: each step's metric is a weighted mean over
        that step's batch; the group value re-weights by the per-step
        weight sums (same identity the epoch aggregation uses). Loss
        keeps sum-over-batch-size semantics (plain mean)."""
        if "_batch_weight" in logs_seq:
            ws = logs_seq["_batch_weight"]
            logs = {}
            for k, v in logs_seq.items():
                if k == "_batch_weight":
                    continue
                logs[k] = (jnp.mean(v) if k == "loss"
                           else _weighted_mean(v, ws))
            logs["_batch_weight"] = jnp.sum(ws)
            return logs
        return {k: jnp.mean(v) for k, v in logs_seq.items()}

    def _make_multi_train_step(self, num_steps, weighted=False,
                               widen=None):
        """ONE XLA executable running `num_steps` optimizer steps via
        `lax.scan` over a leading step axis of stacked batches
        ([num_steps, B, ...] leaves) — Keras `steps_per_execution`,
        TPU-first: per-step host dispatch (66ms round-trips on the
        tunneled chip, PERF.md) amortizes across the whole group, and
        XLA can overlap the next step's transfers with compute.

        Returns (state, logs) with each log the mean over the group
        (weighted runs also return summed "_batch_weight" so epoch
        aggregation stays exact).
        """
        del num_steps  # shape comes from the stacked batch leaves
        inner = self._make_train_step_body(weighted=weighted,
                                           widen=widen)

        def multi_step(state, batches):
            def body(s, batch):
                s, logs = inner(s, batch)
                return s, logs

            state, logs_seq = jax.lax.scan(body, state, batches)
            return state, self._reduce_scan_logs(logs_seq)

        if self._mesh is None:
            return runtime.instrumented_jit(multi_step, donate_argnums=0)
        batch_sharding = sharding_lib.batch_sharding(self._mesh)
        stacked = NamedSharding(
            self._mesh, P(None, *batch_sharding.spec))
        batch_in = ((stacked,) * 3 if weighted
                    else (stacked, stacked))
        return runtime.instrumented_jit(
            multi_step,
            in_shardings=(self._state_sharding, batch_in),
            out_shardings=(self._state_sharding, None),
            donate_argnums=0)

    def _make_resident_run(self, num_steps, steps_per_epoch, resident,
                           weighted):
        """ONE XLA executable advancing `num_steps` optimizer steps
        with ALL data already in HBM (`DeviceResidentDataset`).

        The within-epoch position is derived in-graph from
        `state.step` relative to `base_step` (the step counter at
        epoch entry); the epoch index arrives as `epoch_idx`. Both are
        device scalars, so a call never syncs the host. `epoch_idx` is
        kept in lockstep with the source dataset's `_epoch` counter by
        the fit loop — the host path's shape-inference peek consumes
        one epoch of that counter, and matching it here is what makes
        shuffled resident batches bit-identical to the host path's.
        Shuffled runs rebuild the epoch's permutation with the exact
        `epoch_permutation` doctrine the host path uses (threefry is
        bit-deterministic across backends), then draw each batch with
        `dynamic_slice` of the permutation + `jnp.take`; unshuffled
        runs are a contiguous `dynamic_slice` of the data. The fit
        loop guarantees a call never straddles an epoch boundary (the
        permutation is computed once per call).

        Executables are cached per geometry (`_resident_run_cache`):
        a re-entrant fit over the same dataset — graftguard's warm
        resume, or back-to-back fits — reuses the compiled run instead
        of re-tracing, which is what keeps a resumed resident fit at
        zero new compiles (the retrace sentinel's invariant).
        """
        key = (num_steps, steps_per_epoch, resident.batch_size,
               resident.num_examples, resident.shuffle, resident.seed,
               resident.kind, weighted,
               None if resident.policy is None
               else resident.policy.cache_key)
        cache = getattr(self, "_resident_run_cache", None)
        if cache is None:
            cache = self._resident_run_cache = {}
        cached = cache.get(key)
        if cached is not None:
            run, scalar_set = cached
            # Restore the build-time scalar-metric set: the fit loop's
            # first-step guard reads whatever the (cached) build saw.
            self._train_scalar_unmasked = scalar_set
            return run
        inner = self._make_train_step_body(
            weighted=weighted,
            widen=self._batch_widener(resident.policy, weighted))
        batch_size = resident.batch_size
        num_examples = resident.num_examples
        shuffle = resident.shuffle
        seed = resident.seed

        def run(state, data, base_step, epoch_idx):
            if shuffle:
                key = jax.random.fold_in(jax.random.PRNGKey(seed),
                                         epoch_idx)
                perm = jax.random.permutation(key, num_examples)
            else:
                perm = None

            def one_step(s):
                pos = (s.step - base_step) % steps_per_epoch
                start = pos * batch_size
                if perm is not None:
                    idx = jax.lax.dynamic_slice_in_dim(perm, start,
                                                       batch_size)
                    batch = jax.tree_util.tree_map(
                        lambda a: jnp.take(a, idx, axis=0), data)
                else:
                    batch = jax.tree_util.tree_map(
                        lambda a: jax.lax.dynamic_slice_in_dim(
                            a, start, batch_size), data)
                return inner(s, batch)

            if num_steps == 1:
                return one_step(state)
            state, logs_seq = jax.lax.scan(
                lambda s, _: one_step(s), state, None,
                length=num_steps)
            return state, self._reduce_scan_logs(logs_seq)

        if self._mesh is None:
            jitted = runtime.instrumented_jit(run, donate_argnums=0)
        else:
            jitted = runtime.instrumented_jit(
                run,
                in_shardings=(self._state_sharding, resident.sharding,
                              sharding_lib.replicated(self._mesh),
                              sharding_lib.replicated(self._mesh)),
                out_shardings=(self._state_sharding, None),
                donate_argnums=0)
        cache[key] = (jitted, self._train_scalar_unmasked)
        return jitted

    @staticmethod
    def _metric_accepts_mask(fn):
        """Opt-in masked-metric signature: fn(outputs, y, mask=...).

        The opt-in must be the EXPLICIT named parameter — treating a
        bare ``**kwargs`` as mask-aware would silently hand scalar
        metrics that ignore it an unmasked mean on padded batches, the
        exact leak the mask contract exists to close.
        """
        try:
            params = inspect.signature(fn).parameters
        except (TypeError, ValueError):
            return False
        return "mask" in params

    def _make_eval_step(self):
        metric_fns = self.metric_fns
        loss_fn = self.loss_fn
        eval_kwargs = self.eval_kwargs
        mask_aware = {name: self._metric_accepts_mask(fn)
                      for name, fn in metric_fns.items()}
        # Names of metrics that return a scalar AND can't take the
        # valid-mask: populated at trace time (shape info is static),
        # read by evaluate() to fail loudly on padded tail batches
        # instead of silently averaging padded duplicates in.
        scalar_unmasked = self._scalar_unmasked_metrics = set()

        def eval_step(state, batch):
            # mask flags real examples (times any sample weights);
            # padded tail duplicates (wrapped by ArrayDataset for
            # static shapes) carry zero weight, so metrics are exact
            # example-weighted means.
            x, y, mask = batch
            outputs = self._apply(state.params, x,
                                  extra_vars=state.extra_vars,
                                  **eval_kwargs)
            per_ex = _per_example_view(loss_fn(outputs, y), mask.shape[0])
            logs = {"loss": _weighted_mean(per_ex, mask)}
            for name, fn in metric_fns.items():
                if mask_aware[name]:
                    v = jnp.asarray(fn(outputs, y, mask=mask))
                    if v.ndim >= 1:
                        logs[name] = _weighted_mean(
                            _per_example_view(v, mask.shape[0]), mask)
                    else:
                        # Scalar from a mask-aware fn: it already
                        # weighted out the padded rows.
                        logs[name] = v
                    continue
                v = jnp.asarray(fn(outputs, y))
                if v.ndim >= 1:
                    logs[name] = _weighted_mean(
                        _per_example_view(v, mask.shape[0]), mask)
                else:
                    # Scalar custom metric with no way to apply the
                    # valid-mask: correct on full unweighted batches
                    # only. evaluate() raises otherwise.
                    scalar_unmasked.add(name)
                    logs[name] = v
            # The batch's TOTAL aggregation weight (valid rows x any
            # sample weights), summed over the GLOBAL mask: on pods the
            # host only holds a local shard, so this in-graph sum is
            # the one place the global batch weight exists. evaluate()
            # pops it before reporting.
            logs["_batch_weight"] = jnp.sum(mask)
            return logs

        if self._mesh is None:
            return runtime.instrumented_jit(eval_step)
        batch_sharding = sharding_lib.batch_sharding(self._mesh)
        return runtime.instrumented_jit(
            eval_step,
            in_shardings=(self._state_sharding,
                          (batch_sharding, batch_sharding,
                           batch_sharding)))

    # -- feeding --------------------------------------------------------

    def _feed(self, batch):
        """Host batch -> device batch (global array on multi-host).

        On multi-host pods `batch` must be this process's local shard
        (`_epoch_batches` handles that for ArrayDataset; custom iterables
        must yield process-local batches).
        """
        if self._mesh is None:
            # Commit to device explicitly: jit would transfer uncommitted
            # host arrays itself, but an explicit put (a) is a no-op for
            # already-device-resident arrays, so callers that reuse a
            # batch don't pay the host->device copy per step (the TPU on
            # this host is behind a network tunnel — a 256x224x224x3
            # fp32 batch re-sent every step costs seconds, measured 20x
            # the whole train step), and (b) keeps feeding semantics
            # uniform with the mesh path below.
            runtime.record_h2d(batch)
            return jax.device_put(batch)
        if jax.process_count() > 1:
            return sharding_lib.make_global_batch(batch, self._mesh)
        return sharding_lib.shard_batch(batch, self._mesh)

    def _epoch_batches(self, dataset, start_step=0):
        """One epoch of host batches, process-local on multi-host pods.

        Dispatch on the protocol, not the class: ArrayDataset provides
        `process_local_view`, and wrappers (ThreadedDataset) forward it,
        so pod sharding survives wrapping. `start_step` re-bases the
        epoch mid-stream for graftguard resume: datasets exposing
        `iter_from` skip WITHOUT materializing the prefix (the
        permutation is just sliced further along); anything else pays
        an islice drop of the first `start_step` batches.
        """
        if (jax.process_count() > 1
                and hasattr(dataset, "process_local_view")):
            if start_step:
                return dataset.process_local_view(start_step=start_step)
            return dataset.process_local_view()
        if start_step and hasattr(dataset, "iter_from"):
            return dataset.iter_from(start_step)
        if start_step:
            return itertools.islice(iter(dataset), int(start_step), None)
        return iter(dataset)

    def _host_batches(self, dataset, cast, start_step=0):
        """One epoch of host batches with the `input_cast` narrowing
        applied to the features slot — bytes on the wire drop 2x
        (bfloat16) or 4x (uint8); the jitted step's widener restores
        float32 in-graph."""
        batches = self._epoch_batches(dataset, start_step)
        if cast is None:
            return batches

        def narrowed():
            for batch in batches:
                if isinstance(batch, tuple) and len(batch) == 3:
                    x, y, w = batch
                    yield (cast.host_cast(x), y, w)
                elif isinstance(batch, tuple) and len(batch) == 2:
                    x, y = batch
                    yield (cast.host_cast(x), y)
                else:
                    yield cast.host_cast(batch)
        return narrowed()

    def _pad_tail(self, batch, steady, weighted):
        """Host-side ragged-tail padding: reshapes an n-row tail batch
        to the steady B-row geometry so it dispatches through the
        ALREADY-COMPILED full-shape weighted executable instead of
        minting a one-off ragged variant (a fresh trace + XLA compile
        per distinct tail size — the cost `runtime.compile_stats()`
        exists to pin at zero in steady state).

        Rows wrap (real data, NaN-safe) and the weight vector makes the
        math exact: real rows carry weight * (B/n), wrapped pads carry
        0, so the weighted loss mean(per_ex * w) over B rows equals the
        ragged mean over n rows EXACTLY — gradients included — and
        weighted-mean metrics reduce to means over the real rows (the
        B/n scale cancels).

        Returns ((x, y, w'), real_weight_sum), or None when the
        contract can't hold and the caller must fall back to ragged
        dispatch: multi-process feeding (the scale needs the global
        real count), models that sow losses (the aux-loss mean has no
        weight slot, so wrapped rows would shift gradients), models
        with extra_vars (BatchNorm-style batch statistics would fold
        the wrapped rows in), and unlabeled batches (no (x, y) slots
        to carry a weight alongside).
        """
        if jax.process_count() > 1:
            return None
        if getattr(self, "_sows_losses", False):
            return None
        if (self.state is not None
                and jax.tree_util.tree_leaves(self.state.extra_vars)):
            return None
        if weighted:
            if not (isinstance(batch, tuple) and len(batch) == 3):
                return None
            x, y, w = batch
        elif isinstance(batch, tuple) and len(batch) == 2:
            x, y = batch
            w = None
        else:
            return None
        n = _lead_count(batch)
        if n <= 0 or n >= steady:
            return None
        idx = np.arange(steady) % n
        real = (np.arange(steady) < n).astype(np.float32)
        scale = steady / float(n)
        take = lambda a: np.asarray(a)[idx]
        x_p = jax.tree_util.tree_map(take, x)
        y_p = jax.tree_util.tree_map(take, y)
        if w is None:
            w_p = real * scale
            real_w_sum = float(n)
        else:
            w_np = np.asarray(w, np.float32)
            w_p = w_np[idx] * real * scale
            real_w_sum = float(w_np.sum())
        return (x_p, y_p, w_p), real_w_sum

    def _tail_step_fn(self, weighted, cast):
        """The executable a padded tail dispatches through.

        Weighted fits reuse the fit's own step (the padded triple has
        the steady aval signature — no new trace at all). Unweighted
        fits need the WEIGHTED variant (the pad mask rides in the
        weight slot); it is built once, cached in the ordinary step
        cache (so alternating fits reuse it), and compiles only on the
        first tail of the run — warm for every later epoch.
        """
        if weighted:
            return self._jit_train_step
        key = (True if cast is None else (True, cast.cache_key))
        step_cache = getattr(self, "_train_step_cache", None)
        if step_cache is None:
            step_cache = self._train_step_cache = {}
        if key not in step_cache:
            # _make_train_step_body re-points _train_scalar_unmasked at
            # the new variant's set; restore the fit's own pointer so
            # the first-step guard keeps reading the right slot.
            prev = getattr(self, "_train_scalar_unmasked", set())
            step = self._make_train_step(
                weighted=True, widen=self._batch_widener(cast, True))
            step_cache[key] = (step, self._train_scalar_unmasked)
            self._train_scalar_unmasked = prev
        step, scalar_set = step_cache[key]
        if scalar_set and not getattr(self, "_warned_tail_scalar", False):
            self._warned_tail_scalar = True
            warnings.warn(
                "Custom metrics {} return scalars that cannot be "
                "masked; their logged values for padded tail batches "
                "include the wrapped pad rows (loss, gradients and "
                "per-example metrics stay exact).".format(
                    sorted(scalar_set)))
        return step

    def _fix_tail_logs(self, logs, weighted, real_w_sum):
        """Host-side epoch-aggregation fixup for a padded tail's logs.

        The executable's in-graph `_batch_weight` is sum(w') =
        scale * sum(w) — right for the in-step math, wrong for epoch
        re-weighting, so weighted fits restore the REAL weight sum.
        Unweighted fits strip the key entirely: their epoch aggregation
        is a plain per-step mean and a lone `_batch_weight` entry would
        flip it into the weighted branch.
        """
        logs = dict(logs)
        if weighted:
            logs["_batch_weight"] = jnp.asarray(real_w_sum, jnp.float32)
        else:
            logs.pop("_batch_weight", None)
        return logs

    def _grouped_host_batches(self, batches, limit, spe, pad_tail=None):
        """Yields ("multi", n, stacked_group) for each full group of
        `spe` host batches and ("single", n, batch) for the leftovers —
        the steps_per_execution input shape. With `pad_tail` (a
        callable (batch, steady) -> ((x, y, w'), w_sum) or None),
        ragged leftovers smaller than the steady batch yield
        ("padded", n, padded) so they reuse the full-shape executable
        instead of tracing a one-off ragged variant."""
        steady = None
        group = []

        def emit_single(b):
            n = _lead_count(b)
            if pad_tail is not None and steady is not None and n < steady:
                padded = pad_tail(b, steady)
                if padded is not None:
                    return "padded", n, padded
            return "single", n, b

        for i, batch in enumerate(batches):
            if limit is not None and i >= limit:
                break
            if steady is None:
                steady = _lead_count(batch)
            if group and _lead_count(batch) != _lead_count(group[0]):
                # Ragged batch (e.g. drop_remainder=False tails):
                # np.stack can't group it — flush what we have as
                # singles and keep going.
                for b in group:
                    yield emit_single(b)
                group = []
            group.append(batch)
            if len(group) == spe:
                stacked = jax.tree_util.tree_map(
                    lambda *xs: np.stack(xs), *group)
                yield ("multi", sum(_lead_count(b) for b in group),
                       stacked)
                group = []
        for batch in group:
            yield emit_single(batch)

    def _feed_grouped(self, item):
        """Feed for the steps_per_execution path: stacked groups get
        the [None, dp, ...] layout the multi-step jit expects; leftover
        singles use the ordinary feed. On multi-host pods the stacked
        group holds this process's LOCAL batches; the global array is
        assembled across processes like make_global_batch, one stacking
        level up."""
        kind, _, batch = item
        if kind == "padded":
            # (padded_triple, real_weight_sum): the triple feeds like
            # any single batch; the weight sum stays host-side.
            return self._feed(batch[0])
        if kind == "single":
            return self._feed(batch)
        if self._mesh is None:
            runtime.record_h2d(batch)
            return jax.device_put(batch)
        bs = sharding_lib.batch_sharding(self._mesh)
        stacked = NamedSharding(self._mesh, P(None, *bs.spec))
        if jax.process_count() > 1:
            return sharding_lib.make_global_batch(batch,
                                                  sharding=stacked)
        runtime.record_h2d(batch)
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, stacked), batch)

    def _prefetch_batches(self, batches, limit=None, size=2):
        """Yields (local_example_count, device_batch) with `size` batches
        of read-ahead (see data.prefetch_to_device; this just adds the
        mesh-aware feed and the host-side example count)."""

        def feed(batch):
            lead = next((l for l in jax.tree_util.tree_leaves(batch)
                         if getattr(l, "shape", ())), None)
            n = int(lead.shape[0]) if lead is not None else 0
            return (n, self._feed(batch))

        return data_lib.prefetch_to_device(batches, size=size, feed=feed,
                                           limit=limit)

    # -- AOT warm start -------------------------------------------------

    def _ensure_host_steps(self, weighted, policy):
        """Installs the host-path step executables for this fit's
        variant, through the step cache: alternating
        weighted/unweighted fits reuse each compiled variant instead of
        re-tracing on every flip (bare bool keys; input_cast fits get
        (weighted, policy) tuple keys because the widener is baked into
        the compiled step). Each slot carries its scalar-unmasked set
        (written by that variant's trace), so switching variants
        re-points the guard _fit_epochs reads rather than leaking the
        other slot's names."""
        key = (weighted if policy is None
               else (weighted, policy.cache_key))
        widen = self._batch_widener(policy, weighted)
        step_cache = getattr(self, "_train_step_cache", None)
        if step_cache is None:
            step_cache = self._train_step_cache = {}
        if key not in step_cache:
            step = self._make_train_step(weighted=weighted,
                                         widen=widen)
            step_cache[key] = (step, self._train_scalar_unmasked)
        self._jit_train_step, scalar_set = step_cache[key]
        self._train_scalar_unmasked = (scalar_set if weighted
                                       else set())

        spe = self.steps_per_execution
        self._jit_multi_step = None
        if spe > 1:
            mcache = getattr(self, "_multi_step_cache", None)
            if mcache is None:
                mcache = self._multi_step_cache = {}
            if key not in mcache:
                mcache[key] = self._make_multi_train_step(
                    spe, weighted=weighted, widen=widen)
            self._jit_multi_step = mcache[key]

    def _state_struct(self):
        """ShapeDtypeStructs mirroring the live train state (the AOT
        lowering input; jit's explicit in_shardings supply layouts)."""
        return jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype),
            self.state)

    @staticmethod
    def _batch_struct(batch):
        """ShapeDtypeStructs for a HOST batch, with dtypes
        canonicalized exactly as jit dispatch would (float64 ->
        float32 under the default x64-off), so the AOT executable's
        aval signature matches the real calls."""
        return jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(
                np.shape(l),
                jax.dtypes.canonicalize_dtype(np.asarray(l).dtype)),
            batch)

    @staticmethod
    def _cast_sample(sample, policy):
        """Applies the input_cast host narrowing to a peeked sample so
        warm-start structs see the on-the-wire dtypes."""
        if policy is None:
            return sample
        if isinstance(sample, tuple) and len(sample) == 3:
            x, y, w = sample
            return (policy.host_cast(x), y, w)
        if isinstance(sample, tuple) and len(sample) == 2:
            x, y = sample
            return (policy.host_cast(x), y)
        return policy.host_cast(sample)

    def _warm_fit_steps(self, sample, weighted, policy):
        """AOT-compiles (`lower().compile()`) the installed fit
        executables for this fit's data geometry. The compiled
        executables land in each wrapper's warm table, so the epoch
        loop's first dispatch runs them directly — no trace, no
        compile, `runtime.compile_stats()` unmoved by step 1."""
        del weighted  # geometry comes from the sample itself
        state_struct = self._state_struct()
        batch_struct = self._batch_struct(
            self._cast_sample(sample, policy))
        self._jit_train_step.warm(state_struct, batch_struct)
        if getattr(self, "_jit_multi_step", None) is not None:
            spe = self.steps_per_execution
            stacked = jax.tree_util.tree_map(
                lambda s: jax.ShapeDtypeStruct(
                    (spe,) + tuple(s.shape), s.dtype), batch_struct)
            self._jit_multi_step.warm(state_struct, stacked)

    def warmup(self, x, y=None, batch_size=32, sample_weight=None,
               input_cast=None, include_eval=False,
               include_predict=False):
        """AOT-compiles the step executables for a data geometry,
        ahead of (and without) running any training.

        The standalone form of `fit(warm_start=True)`: builds the model
        from a sample batch, installs the train-step executables for
        the (batch_size, weighted, input_cast) variant, and
        `lower().compile()`s them from ShapeDtypeStructs. A subsequent
        `fit()` over the same geometry starts trace-free, and with the
        persistent compile cache enabled
        (`parallel.compile_cache.enable`) a restarted process pays
        deserialization, not XLA, here.

        include_eval / include_predict additionally warm the
        evaluate() / predict() executables for the same batch geometry
        (include_eval needs labels `y`).

        Returns `runtime.compile_stats()` after warming (the warm-up's
        own compiles are visible there; steady-state assertions should
        snapshot AFTER warmup returns).
        """
        ds_kwargs = {}
        if sample_weight is not None:
            ds_kwargs["sample_weight"] = np.asarray(sample_weight,
                                                    np.float32)
        dataset = data_lib.as_dataset(x, y, batch_size=batch_size,
                                      shuffle=False, **ds_kwargs)
        weighted = (isinstance(dataset, data_lib.ArrayDataset)
                    and dataset.sample_weight is not None)
        sample = next(iter(dataset))
        sample_x = sample[0] if isinstance(sample, tuple) else sample
        self.build(sample_x)
        policy = None
        if input_cast not in (None, "none"):
            if isinstance(dataset, data_lib.ArrayDataset):
                policy = data_lib.make_input_cast(input_cast, dataset.x)
            else:
                policy = data_lib.make_input_cast(input_cast, sample_x)
        self._ensure_host_steps(weighted, policy)
        self._warm_fit_steps(sample, weighted, policy)
        state_struct = self._state_struct()
        if include_eval:
            if not (isinstance(sample, tuple) and len(sample) >= 2):
                raise ValueError(
                    "warmup(include_eval=True) needs labels y.")
            if self._jit_eval_step is None:
                self._jit_eval_step = self._make_eval_step()
            xb, yb = sample[0], sample[1]
            mask = jax.ShapeDtypeStruct((_lead_count(sample),),
                                        jnp.float32)
            self._jit_eval_step.warm(
                state_struct, (self._batch_struct(xb),
                               self._batch_struct(yb), mask))
        if include_predict:
            if self._jit_predict_step is None:
                self._jit_predict_step = self._make_predict_step()
            self._jit_predict_step.warm(
                state_struct, self._batch_struct(sample_x))
        return runtime.compile_stats()

    def _maybe_capture_step_flops(self, fn, n_steps, *args):
        """Captures model flops per TRAIN STEP for the graftscope MFU
        gauge, once per enabled telemetry session.

        Uses jit cost analysis on a lowering of the step executable
        (`fn.lower(*args).cost_analysis()['flops']` — no XLA compile),
        divided by `n_steps` for grouped/resident executables that run
        several steps per dispatch. Called at the FIRST dispatch of a
        fit, before the call consumes its donated buffers; the extra
        trace lands in epoch 0, ahead of the retrace-sentinel baseline.
        No-ops (one dict lookup) when telemetry is off.
        """
        telemetry = sys.modules.get("cloud_tpu.monitoring.telemetry")
        if telemetry is None:
            return
        tele = telemetry.get()
        if tele is None or not tele.active or tele.step_flops:
            return
        try:
            analysis = fn.lower(*args).cost_analysis()
            if isinstance(analysis, (list, tuple)):
                analysis = analysis[0] if analysis else {}
            flops = float(analysis.get("flops", 0.0) or 0.0)
            if flops > 0:
                tele.set_step_flops(flops / max(int(n_steps), 1))
        except Exception:  # telemetry must never break training
            logger.debug("step-flops capture failed", exc_info=True)

    # -- public API -----------------------------------------------------

    @_env_watched
    @_env_telemetry
    @_env_sanitized
    def fit(self,
            x=None,
            y=None,
            epochs=1,
            batch_size=32,
            shuffle=True,
            validation_data=None,
            validation_split=0.0,
            initial_epoch=0,
            callbacks=(),
            steps_per_epoch=None,
            verbose=True,
            resume_from=None,
            prefetch=2,
            sample_weight=None,
            class_weight=None,
            cache=None,
            input_cast=None,
            async_logging=True,
            warm_start=False,
            on_retrace=None,
            resume=None,
            retries=None):
        """Trains the model; returns a history dict of per-epoch logs.

        resume: "auto" runs the fit under graftguard
        (`resilience.resilient_fit`): typed faults — the watchdog's
        `BackendUnavailable`, `Preemption`, `CheckpointCorrupt`,
        `DataStall`, `TerminateOnNaN(rollback=True)`'s `NaNLoss` — are
        caught, answered with a rescue/rollback checkpoint, and
        retried with capped exponential backoff; re-entry restores the
        latest checkpoint, re-bases the shuffle stream to the saved
        mid-epoch position (bit-identical continuation), and reuses
        the warm executables (zero new compiles). The checkpoint
        directory is `resume_from` (else `CLOUD_TPU_RESUME_DIR`, else
        `./graftguard_ckpt`), auto-checkpointed every epoch.

        retries: graftguard's retry budget (with resume="auto" only);
        default `CLOUD_TPU_RETRIES` (3).

        warm_start: AOT-compile the fit executables (train step, and
        the steps_per_execution / device-resident variants) from
        `ShapeDtypeStruct`s BEFORE the epoch loop — step 1 dispatches a
        finished executable without tracing anything
        (`runtime.compile_stats()` does not move on the first step).
        The same executables are also eligible for the persistent
        compile cache (`parallel.compile_cache.enable`), making the
        warm-up near-free on a restart.

        on_retrace: The retrace sentinel's policy — "warn" (default;
        also via the CLOUD_TPU_ON_RETRACE env var), "raise", or
        "ignore". After the first completed epoch (whose compiles are
        legitimate: the step executables, validation, callbacks), a
        steady-state epoch that traces or compiles ANYTHING raises/
        warns `runtime.RetraceWarning` — the counted invariant is zero
        new compiles after epoch 1, and the usual culprits (ragged
        tails, input dtype drift) are bugs worth hearing about.

        async_logging: The async host loop (default on). Epoch metrics
        stay device scalars, coalesce into ONE pytree, and are fetched
        by a background reader thread — the train loop never blocks on
        a device->host round trip unless a callback actually reads a
        metric value (callbacks receive a lazily-resolving logs dict).
        False fetches synchronously at each epoch boundary — still one
        coalesced fetch per epoch, and bit-identical values (the
        device-side aggregation is shared). Either way
        `runtime.transfer_stats()["d2h_fetches"]` counts at most one
        fetch per logging interval. Fetch errors from the background
        thread re-raise on the training thread at the next epoch
        boundary (or at fit exit for the last epoch).

        cache: "device" uploads the whole dataset to device HBM ONCE
        and draws every batch in-graph (device-side per-epoch
        permutation + dynamic_slice/take): steady-state training does
        zero host->device data transfers while keeping `shuffle=True`
        semantics (same threefry permutation as the host path) and
        composing with steps_per_execution and gradient accumulation.
        Array inputs that fit the HBM budget only — anything else
        falls back to host streaming with one warning line (see
        data.DeviceResidentDataset.build).

        input_cast: Transfer policy narrowing features on the wire —
        "bfloat16" (2x fewer bytes, works on any input) or "uint8"
        (4x fewer bytes, affine-quantized; array inputs only, since
        lo/scale calibrate on the full arrays). The jitted step widens
        back to float32 in-graph, so the model's compute dtype is
        unchanged. Composes with cache="device" (the resident copy
        stays narrow in HBM).

        prefetch: Device read-ahead depth — `prefetch` batches are kept
        in flight ahead of the one being consumed (up to prefetch+1
        resident). 0 feeds synchronously, the minimal-HBM mode for
        workloads already near capacity.

        resume_from: Optional checkpoint directory (a ModelCheckpoint
        filepath from an earlier run). When it holds a checkpoint, the
        full train state (params, optimizer state, step, rng) is
        restored before training — the failure-recovery path the
        reference leaves to manual SavedModel reloads (and explicitly
        does not support for remote tuner trials, reference
        tuner/tuner.py:562-567). Missing/empty directories are ignored,
        so a preemption-restart loop can always pass it.

        sample_weight: Optional [num_examples] per-example weights
        (Keras `fit(sample_weight=)`): the loss becomes
        mean(per_example * w) and per-example metrics weighted means.
        Array inputs only; `validation_data` may be (x, y, w) too.

        validation_split: Keras parity — float in (0, 1): hold out the
        LAST fraction of the (un-shuffled) input arrays as validation
        data, weights included; mutually exclusive with
        validation_data, array inputs only. Training shuffle (if on)
        applies only to the retained training fraction, like Keras.

        initial_epoch: Keras parity — epoch index to start from
        (epochs still names the FINAL epoch bound, so `epochs=10,
        initial_epoch=4` runs 6 epochs numbered 4..9); pairs with
        `resume_from=` so callback epoch numbering and schedules
        driven by epoch continue where the interrupted run stopped.

        class_weight: Optional {label: weight} dict (Keras
        `fit(class_weight=)`) for imbalanced classification — sugar
        for a per-example sample_weight derived from integer labels
        `y` (multiplies into any explicit sample_weight). Labels
        absent from the dict weigh 1.0.
        """
        kwargs = dict(
            x=x, y=y, epochs=epochs, batch_size=batch_size,
            shuffle=shuffle, validation_data=validation_data,
            validation_split=validation_split,
            initial_epoch=initial_epoch, callbacks=callbacks,
            steps_per_epoch=steps_per_epoch, verbose=verbose,
            resume_from=resume_from, prefetch=prefetch,
            sample_weight=sample_weight, class_weight=class_weight,
            cache=cache, input_cast=input_cast,
            async_logging=async_logging, warm_start=warm_start,
            on_retrace=on_retrace)
        if resume in (None, False, "none"):
            if retries is not None:
                raise ValueError(
                    "retries= only applies with resume='auto'.")
            return self._fit_impl(**kwargs)
        if resume != "auto":
            raise ValueError(
                "resume must be 'auto' or None; got {!r}.".format(resume))
        from cloud_tpu.training import resilience

        return resilience.resilient_fit(self, retries=retries, **kwargs)

    def _fit_impl(self,
                  x=None,
                  y=None,
                  epochs=1,
                  batch_size=32,
                  shuffle=True,
                  validation_data=None,
                  validation_split=0.0,
                  initial_epoch=0,
                  callbacks=(),
                  steps_per_epoch=None,
                  verbose=True,
                  resume_from=None,
                  prefetch=2,
                  sample_weight=None,
                  class_weight=None,
                  cache=None,
                  input_cast=None,
                  async_logging=True,
                  warm_start=False,
                  on_retrace=None,
                  data_seed=None,
                  history=None):
        """One fit attempt — `fit`'s whole body, minus the graftguard
        dispatch. The retry loop calls this directly (inside fit's
        env scopes, so the watchdog/telemetry/sanitizer persist across
        attempts) with two extras: `data_seed` overrides the dataset
        shuffle seed (NaN rollback resumes with a fresh data order)
        and `history` accumulates one dict ACROSS attempts.
        """
        if validation_split:
            if not 0.0 < validation_split < 1.0:
                raise ValueError(
                    "validation_split must be in (0, 1); got {}."
                    .format(validation_split))
            if validation_data is not None:
                raise ValueError(
                    "Pass validation_split OR validation_data, not "
                    "both.")
            if y is None or not (
                    hasattr(x, "shape") or isinstance(x, (dict, list,
                                                          tuple))):
                raise ValueError(
                    "validation_split needs raw array inputs (x, y); "
                    "datasets should pre-split and pass "
                    "validation_data.")
            n = jax.tree_util.tree_leaves(x)[0].shape[0]
            split = int(n * (1.0 - validation_split))
            if split == 0 or split == n:
                raise ValueError(
                    "validation_split={} leaves an empty {} set for {}"
                    " examples.".format(
                        validation_split,
                        "training" if split == 0 else "validation", n))
            # Keras semantics: the LAST fraction, taken before any
            # shuffling, is validation.
            take = lambda lo, hi: jax.tree_util.tree_map(
                lambda a: a[lo:hi], x)
            y_arr = np.asarray(y)
            if sample_weight is not None:
                sw = np.asarray(sample_weight, np.float32)
                validation_data = (take(split, n), y_arr[split:],
                                   sw[split:])
                sample_weight = sw[:split]
            else:
                validation_data = (take(split, n), y_arr[split:])
            x, y = take(0, split), y_arr[:split]
        if class_weight is not None:
            labels = None if y is None else np.asarray(y)
            if labels is None or labels.ndim != 1:
                raise ValueError(
                    "class_weight= needs 1-D integer labels `y`.")
            cw = np.ones(labels.shape[0], np.float32)
            for label, weight in class_weight.items():
                cw[labels == label] = float(weight)
            sample_weight = (cw if sample_weight is None
                             else np.asarray(sample_weight,
                                             np.float32) * cw)
        if sample_weight is not None and not (
                hasattr(x, "shape") or isinstance(x, (dict, list, tuple))):
            # Pre-built datasets ignore as_dataset kwargs — silently
            # dropping the weights would train unweighted.
            raise ValueError(
                "sample_weight= needs raw array inputs; pre-built "
                "datasets carry their own weights via "
                "ArrayDataset(sample_weight=...).")
        ds_kwargs = {}
        if sample_weight is not None:
            ds_kwargs["sample_weight"] = sample_weight
        dataset = data_lib.as_dataset(
            x, y, batch_size=batch_size, shuffle=shuffle,
            seed=(self.seed if data_seed is None else data_seed),
            **ds_kwargs)
        if (sample_weight is not None
                and not isinstance(dataset, data_lib.ArrayDataset)):
            raise ValueError(
                "sample_weight= needs array inputs (datasets carry "
                "their own weights by yielding (x, y, w) via "
                "ArrayDataset(sample_weight=...)).")
        weighted = (isinstance(dataset, data_lib.ArrayDataset)
                    and dataset.sample_weight is not None)
        if steps_per_epoch is None:
            # Dataset-level cap (e.g. GeneratorDataset over an unbounded
            # stream) applies when the caller sets none.
            steps_per_epoch = getattr(dataset, "steps_per_epoch", None)
        # Safe to peek: as_dataset returns re-iterables only (one-shot
        # iterators were materialized into a list).
        sample = next(iter(dataset))
        sample_x = sample[0] if isinstance(sample, tuple) else sample
        self.build(sample_x)
        start_step = 0
        if resume_from is not None:
            from cloud_tpu.training import checkpoint as checkpoint_lib
            ckpt_step = checkpoint_lib.latest_step(resume_from)
            if ckpt_step is not None:
                # CheckpointCorrupt propagates from here to graftguard,
                # which quarantines the step and re-enters on the
                # previous one.
                self.state = checkpoint_lib.restore(resume_from,
                                                    self.state,
                                                    step=ckpt_step)
                logger.info("Resumed training from %s at step %d.",
                            resume_from, int(self.state.step))
                meta = checkpoint_lib.load_metadata(resume_from,
                                                    ckpt_step) or {}
                initial_epoch, start_step = self._apply_data_state(
                    dataset, meta.get("data_state"), initial_epoch,
                    data_seed)

        policy = None
        if input_cast not in (None, "none"):
            if isinstance(dataset, data_lib.ArrayDataset):
                policy = data_lib.make_input_cast(input_cast, dataset.x)
            elif (input_cast in ("bfloat16", "bf16")
                  or isinstance(input_cast, data_lib.InputCast)):
                # Parameterless policies calibrate from the sample.
                policy = data_lib.make_input_cast(input_cast, sample_x)
            else:
                raise ValueError(
                    "input_cast='uint8' calibrates lo/scale from the "
                    "full arrays and needs array inputs; streaming "
                    "datasets support input_cast='bfloat16'.")

        resident = None
        if cache not in (None, "none", False):
            if cache != "device":
                raise ValueError(
                    "Unknown cache={!r}; expected 'device'.".format(
                        cache))
            resident = data_lib.DeviceResidentDataset.build(
                dataset, input_cast=policy, mesh=self._mesh)

        # Resident fits build their executables through the
        # per-geometry _resident_run_cache (the permutation geometry is
        # baked into the key) and skip the host step caches.
        if resident is None:
            self._ensure_host_steps(weighted, policy)
            if warm_start:
                self._warm_fit_steps(sample, weighted, policy)

        history = {} if history is None else history
        self.stop_training = False
        self._abort_epoch = False
        # graftchaos arm: only when the chaos module is loaded (a test
        # installed a plan) or CLOUD_TPU_CHAOS asks for it — the normal
        # fit path stays import- and branch-free in the hot loop.
        chaos_mod = sys.modules.get("cloud_tpu.analysis.chaos")
        if chaos_mod is None and os.environ.get("CLOUD_TPU_CHAOS"):
            from cloud_tpu.analysis import chaos as chaos_mod
        self._chaos = None if chaos_mod is None else chaos_mod.active_plan()
        # Retrace sentinel state (see on_retrace above): the baseline
        # is snapshotted at the end of the first COMPLETED epoch; the
        # counters are process-wide, so a second Trainer compiling
        # mid-fit also trips it (that, too, is compile traffic the
        # steady state shouldn't have).
        self._retrace_baseline = None
        self._warned_tail_scalar = False
        on_retrace = (on_retrace
                      or os.environ.get("CLOUD_TPU_ON_RETRACE")
                      or "warn")
        if on_retrace not in ("warn", "raise", "ignore"):
            raise ValueError(
                "on_retrace must be 'warn', 'raise' or 'ignore'; got "
                "{!r}.".format(on_retrace))
        self._on_retrace = on_retrace
        # Async host loop state: one reader thread per Trainer (reused
        # across fits — the thread is lazy and survives idle), one
        # pending-history list per fit (drained at the exit barrier).
        self._async_logging = bool(async_logging)
        if getattr(self, "_metric_reader", None) is None:
            self._metric_reader = async_logs_lib.AsyncMetricReader()
        self._pending_history = []
        # Visible to callbacks at on_train_begin (e.g. ProfilerCallback
        # checks its target epochs will actually run). The epoch range
        # of THIS fit is [initial_epoch, planned_epochs).
        self.planned_epochs = epochs
        self.initial_epoch = initial_epoch
        for cb in callbacks:
            cb.set_trainer(self)
            cb.on_train_begin()

        try:
            if resident is not None:
                self._fit_epochs_resident(
                    resident, epochs, steps_per_epoch, validation_data,
                    batch_size, callbacks, history, verbose, prefetch,
                    initial_epoch=initial_epoch, warm_start=warm_start,
                    start_step=start_step)
            else:
                self._fit_epochs(dataset, epochs, steps_per_epoch,
                                 validation_data, batch_size, callbacks,
                                 history, verbose, prefetch,
                                 initial_epoch=initial_epoch,
                                 cast=policy, weighted=weighted,
                                 start_step=start_step)
        finally:
            # The epoch loops label this thread "step"/"boundary" for
            # graftsan; an abort can exit mid-"step". Clear the label so
            # post-fit host code is never counted against the step loop.
            runtime.set_phase(None)
            # Guaranteed even when a train step raises (OOM, interrupt):
            # callbacks holding external resources (profiler traces,
            # open files) rely on on_train_end for cleanup. Isolated per
            # callback so one failing teardown (e.g. an async checkpoint
            # commit error) cannot skip the others; the first error
            # still surfaces after all have run.
            teardown_error = None
            # The async host loop's exit barrier, BEFORE on_train_end:
            # materialize the deferred per-epoch history appends so
            # callbacks reading `history` at teardown (and the caller)
            # see every epoch. A failed background fetch surfaces here
            # like a teardown error — after the remaining epochs
            # drained, without masking a propagating train exception.
            try:
                self._materialize_history(history)
            except Exception as e:  # noqa: BLE001 - must not mask
                logger.exception("deferred metric fetch failed")
                teardown_error = e
            for cb in callbacks:
                try:
                    cb.on_train_end(history)
                except Exception as e:  # noqa: BLE001 - must not mask
                    logger.exception("on_train_end failed for %r", cb)
                    if teardown_error is None:
                        teardown_error = e
            # Async checkpoint drain on EVERY fit exit path (normal,
            # EarlyStopping/request_stop, raising train step): without
            # this, fit could return — or the process exit — with a
            # background Orbax write still in flight, and the caller's
            # "training finished" would race a torn checkpoint.
            # sys.modules.get: if nothing ever imported checkpoint
            # (and so no async save can be pending), don't pull in
            # orbax just to ask.
            ckpt_lib = sys.modules.get("cloud_tpu.training.checkpoint")
            if ckpt_lib is not None:
                try:
                    ckpt_lib.wait_until_finished()
                except Exception as e:  # noqa: BLE001 - must not mask
                    logger.exception("async checkpoint drain failed")
                    if teardown_error is None:
                        teardown_error = e
            # Surface a teardown failure only when no training exception
            # is already propagating — raising inside `finally` would
            # replace it, hiding the error that actually killed the run.
            if teardown_error is not None and sys.exc_info()[1] is None:
                raise teardown_error
        return history

    def _materialize_history(self, history):
        """Drains `_pending_history` into `history` (the exit barrier).

        Each record is (future, device_key_order, host_items): device
        metrics first, then host-side entries (steps_per_sec, val_*) —
        the same key order the eager path always produced. The first
        future whose fetch failed re-raises AFTER the loop so every
        healthy epoch still lands in history.
        """
        pending, self._pending_history = self._pending_history, []
        fetch_error = None
        for future, dev_keys, host_items in pending:
            resolved = {}
            if future is not None:
                try:
                    resolved = future.result()
                except Exception as e:  # noqa: BLE001 - raised below
                    if fetch_error is None:
                        fetch_error = e
                    continue
            for k in dev_keys:
                history.setdefault(k, []).append(resolved[k])
            for k, v in host_items.items():
                history.setdefault(k, []).append(v)
        if fetch_error is not None:
            raise fetch_error

    def request_stop(self):
        """Stops training at the next step boundary (signal-safe).

        The preemption hook: sets two plain host flags — the step loop
        breaks out of the current epoch at its next iteration (no
        device sync, no interrupted collective), the partial epoch
        still runs its epoch-end callbacks (so ModelCheckpoint /
        PreemptionCheckpoint save a resumable state), and fit()
        returns. Safe to call from a signal handler or another thread.
        """
        self._abort_epoch = True
        self.stop_training = True

    # -- graftguard: the resumable data-stream position ----------------

    def current_data_state(self):
        """The resumable data-stream position, for checkpoint metadata.

        Returns `{"epoch", "step_in_epoch", "dataset_epoch",
        "data_seed"}` describing where the shuffle stream stands as of
        the CURRENT train state, or None outside a fit. `step_in_epoch`
        derives from the step counter itself (`state.step` minus the
        epoch's base step, one device read at save time) rather than
        host-side bookkeeping, so a watchdog fault async-raised between
        a dispatch and its bookkeeping still checkpoints a position
        consistent with the params — resume never double-applies a
        step. Positions at the epoch boundary normalize to
        `(epoch + 1, 0)`.
        """
        progress = self._data_progress
        if progress is None or self.state is None:
            return None
        try:
            step_in_epoch = max(
                int(self.state.step) - progress["epoch_base"], 0)
        except Exception:
            # Donated/invalidated buffers (a fault landed mid-dispatch):
            # no trustworthy position — and no trustworthy state to
            # save it with either.
            return None
        epoch = int(progress["epoch"])
        dataset_epoch = int(progress["dataset_epoch"])
        spe = progress.get("steps_per_epoch")
        if spe and step_in_epoch >= spe:
            rolls = step_in_epoch // spe
            epoch += rolls
            dataset_epoch += rolls
            step_in_epoch -= rolls * spe
        return {"epoch": epoch, "step_in_epoch": step_in_epoch,
                "dataset_epoch": dataset_epoch,
                "data_seed": progress.get("data_seed")}

    def _apply_data_state(self, dataset, data_state, initial_epoch,
                          data_seed):
        """Re-bases the shuffle stream to a checkpoint's mid-epoch
        position (graftguard exact resume); returns the effective
        `(initial_epoch, start_step)`.

        The metadata carries `(epoch, step_in_epoch, dataset_epoch,
        data_seed)` as of the save. When the live dataset draws from
        the same seed, its epoch counter is rewound to the in-progress
        epoch's value (overwriting the tick this fit's shape-inference
        peek consumed) and the fit loop skips the epoch's first
        `step_in_epoch` batches — the resumed run continues the
        interrupted threefry permutation exactly, and with the per-step
        train rng keyed off the restored global step, the loss
        trajectory is bit-identical to an uninterrupted run. A
        DIFFERENT seed (NaN rollback resumes with a fresh data-order
        rng) instead restarts the interrupted epoch from batch 0 under
        the new permutation.
        """
        if not data_state:
            return initial_epoch, 0
        epoch = int(data_state.get("epoch", initial_epoch))
        step_in_epoch = int(data_state.get("step_in_epoch", 0))
        dataset_epoch = data_state.get("dataset_epoch")
        seed_then = data_state.get("data_seed")
        seed_now = getattr(
            dataset, "seed", self.seed if data_seed is None else data_seed)
        initial_epoch = max(initial_epoch, epoch)
        if dataset_epoch is not None and hasattr(dataset, "_epoch"):
            dataset._epoch = int(dataset_epoch)
        if seed_then is not None and seed_then != seed_now:
            logger.info(
                "Resuming epoch %d from its start with a fresh data "
                "order (seed %s -> %s).", epoch, seed_then, seed_now)
            return initial_epoch, 0
        if step_in_epoch:
            logger.info("Resuming mid-epoch: epoch %d, batch %d.",
                        epoch, step_in_epoch)
        return initial_epoch, step_in_epoch

    def _note_dispatch_done(self):
        """Per-dispatch epilogue shared by the fit loops: the watchdog
        step beat, then the one-shot graftguard resume probe (latency +
        compile delta after the first completed dispatch of a resumed
        attempt)."""
        watch_lib.notify_step()
        probe = self._resume_probe
        if probe is not None:
            self._resume_probe = None
            probe.first_step()

    def _fit_epochs(self, dataset, epochs, steps_per_epoch,
                    validation_data, batch_size, callbacks, history,
                    verbose, prefetch=2, initial_epoch=0, cast=None,
                    weighted=False, start_step=0):
        pad_tail = lambda b, steady: self._pad_tail(b, steady, weighted)
        # Feeder items are (kind, examples, tail_weight_sum, batch):
        # the weight sum is only meaningful for "padded" tails (the
        # host-side value _fix_tail_logs restores into the epoch
        # aggregation); everything else carries None.
        unpack = lambda item: (
            item[0], item[1],
            item[2][1] if item[0] == "padded" else None)
        # Host mirror of the global step at epoch entry: ONE boundary
        # sync per fit (the scalar is quiescent here), advanced by the
        # host step count at each epoch end. current_data_state
        # subtracts it from the live step counter to get the mid-epoch
        # position without trusting hot-loop bookkeeping.
        host_base = int(self.state.step)
        for epoch in range(initial_epoch, epochs):
            epoch_start = int(start_step) if epoch == initial_epoch else 0
            if steps_per_epoch is not None:
                epoch_start = min(epoch_start, steps_per_epoch)
            self._data_progress = {
                "epoch": epoch,
                "epoch_base": host_base - epoch_start,
                # Recorded BEFORE iteration advances it: the value this
                # epoch's permutation will draw from.
                "dataset_epoch": int(getattr(dataset, "_epoch", 0)),
                "steps_per_epoch": steps_per_epoch,
                "data_seed": getattr(dataset, "seed", None),
            }
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            step_logs = []
            count = 0
            examples = 0
            t0 = time.time()
            # Thread label for graftsan: a device fetch from inside the
            # step loop is the violation the sanitizer exists to catch;
            # _post_epoch_logs flips the label back to "boundary" where
            # the per-epoch coalesced fetch is sanctioned.
            runtime.set_phase("step")
            # graftscope: the whole step-loop section is one "step"
            # span; each feeder iteration becomes a "train_step" span
            # containing "data_wait" + "dispatch". begin() is None and
            # trace_steps is skipped when telemetry is off, so the
            # disabled hot loop is unchanged.
            step_section = spans_lib.begin("step")
            spe = self.steps_per_execution
            multi_step = getattr(self, "_jit_multi_step", None)
            if spe > 1 and multi_step is not None:
                epoch_limit = (None if steps_per_epoch is None
                               else steps_per_epoch - epoch_start)
                feeder = data_lib.prefetch_to_device(
                    self._grouped_host_batches(
                        self._host_batches(dataset, cast,
                                           start_step=epoch_start),
                        epoch_limit, spe, pad_tail=pad_tail),
                    size=prefetch,
                    feed=lambda item: unpack(item) + (
                        self._feed_grouped(item),))
                if spans_lib.enabled():
                    feeder = spans_lib.trace_steps(feeder)
                first = True
                for kind, batch_examples, w_sum, fed in feeder:
                    if self._abort_epoch:
                        break
                    if self._chaos is not None:
                        self._chaos.pre_dispatch(
                            host_base + count,
                            spe if kind == "multi" else 1)
                    examples += batch_examples
                    if kind == "multi":
                        if first and epoch == initial_epoch:
                            self._maybe_capture_step_flops(
                                multi_step, spe, self.state, fed)
                        with spans_lib.span("dispatch"):
                            self.state, logs = multi_step(self.state,
                                                          fed)
                        if "_batch_weight" in logs:
                            # The group log already carries the GROUP
                            # weight sum: append once (duplicating
                            # would double-weight groups vs leftover
                            # singles in the epoch re-weighting). The
                            # loss, however, is a plain per-step mean
                            # (Keras sum-over-batch-size semantics), so
                            # the entry must record how many steps it
                            # stands for or a group would count equal
                            # to one leftover single batch.
                            logs = dict(logs)
                            logs["_steps"] = spe
                            step_logs.append(logs)
                        else:
                            # Unweighted epoch mean is a per-step mean:
                            # the group mean stands for `spe` steps.
                            step_logs.extend([logs] * spe)
                        count += spe
                    elif kind == "padded":
                        tail_step = self._tail_step_fn(weighted, cast)
                        with spans_lib.span("dispatch"):
                            self.state, logs = tail_step(self.state,
                                                         fed)
                        step_logs.append(self._fix_tail_logs(
                            logs, weighted, w_sum))
                        count += 1
                    else:
                        with spans_lib.span("dispatch"):
                            self.state, logs = self._jit_train_step(
                                self.state, fed)
                        step_logs.append(logs)
                        count += 1
                    if (first and epoch == initial_epoch
                            and getattr(self, "_train_scalar_unmasked",
                                        None)):
                        # Same loud failure as the single-step path: a
                        # scalar metric can't be sample-weighted.
                        raise ValueError(
                            "Custom metrics {} return a scalar and "
                            "cannot apply sample_weight. Give them a "
                            "mask-aware signature "
                            "fn(outputs, y, mask=...) or return "
                            "per-example values.".format(
                                sorted(self._train_scalar_unmasked)))
                    # graftwatch: one completed dispatch = one beat
                    # (one global load + None check when unwatched),
                    # plus the one-shot graftguard resume probe.
                    self._note_dispatch_done()
                    first = False
                spans_lib.end(step_section)
                host_base += count
                if not (self._abort_epoch and count == 0):
                    # A zero-step aborted epoch has no metrics; an
                    # epoch-end with only steps_per_sec would desync
                    # history keys and hand callbacks a loss-less epoch.
                    self._post_epoch_logs(step_logs, count, examples,
                                          t0, epoch, validation_data,
                                          batch_size, callbacks,
                                          history, verbose, prefetch)
                if self.stop_training:
                    break
                continue
            epoch_bound = (None if steps_per_epoch is None
                           else steps_per_epoch - epoch_start)

            def singles():
                # The limit check precedes the pull: a bounded stream
                # (steps_per_epoch over an expensive generator) must
                # never be drawn past the bound.
                steady = None
                it = iter(self._host_batches(dataset, cast,
                                             start_step=epoch_start))
                i = 0
                while epoch_bound is None or i < epoch_bound:
                    try:
                        b = next(it)
                    except StopIteration:
                        break
                    i += 1
                    n = _lead_count(b)
                    if steady is None:
                        steady = n
                    if n < steady:
                        padded = pad_tail(b, steady)
                        if padded is not None:
                            yield "padded", n, padded
                            continue
                    yield "single", n, b

            feeder = data_lib.prefetch_to_device(
                singles(), size=prefetch,
                feed=lambda item: unpack(item) + (
                    self._feed(item[2][0] if item[0] == "padded"
                               else item[2]),))
            if spans_lib.enabled():
                feeder = spans_lib.trace_steps(feeder)
            for kind, batch_examples, w_sum, batch in feeder:
                if self._abort_epoch:
                    break
                if self._chaos is not None:
                    self._chaos.pre_dispatch(host_base + count, 1)
                examples += batch_examples
                if kind == "padded":
                    tail_step = self._tail_step_fn(weighted, cast)
                    with spans_lib.span("dispatch"):
                        self.state, logs = tail_step(self.state, batch)
                    logs = self._fix_tail_logs(logs, weighted, w_sum)
                else:
                    if count == 0 and epoch == initial_epoch:
                        self._maybe_capture_step_flops(
                            self._jit_train_step, 1, self.state, batch)
                    with spans_lib.span("dispatch"):
                        self.state, logs = self._jit_train_step(
                            self.state, batch)
                if (count == 0 and epoch == initial_epoch
                        and getattr(self, "_train_scalar_unmasked", None)):
                    # Populated during the trace that just ran: a
                    # scalar metric can't be sample-weighted — fail
                    # loudly like evaluate() does, instead of logging
                    # unweighted numbers for the whole run.
                    raise ValueError(
                        "Custom metrics {} return a scalar and cannot "
                        "apply sample_weight. Give them a mask-aware "
                        "signature fn(outputs, y, mask=...) or return "
                        "per-example values.".format(
                            sorted(self._train_scalar_unmasked)))
                # Keep logs as device arrays: no host sync inside the hot
                # loop (async dispatch overlaps host batching with the
                # device step); convert once per epoch below.
                step_logs.append(logs)
                count += 1
                # graftwatch beat + graftguard resume probe.
                self._note_dispatch_done()
            spans_lib.end(step_section)
            host_base += count
            if not (self._abort_epoch and count == 0):
                # Same zero-step-abort guard as the multi-step path.
                self._post_epoch_logs(step_logs, count, examples, t0,
                                      epoch, validation_data,
                                      batch_size, callbacks, history,
                                      verbose, prefetch)
            if self.stop_training:
                break

    def _fit_epochs_resident(self, resident, epochs, steps_per_epoch,
                             validation_data, batch_size, callbacks,
                             history, verbose, prefetch=2,
                             initial_epoch=0, warm_start=False,
                             start_step=0):
        """The device-resident fit loop: every batch is drawn in-graph
        from `resident.data`, so the epoch loop issues executable calls
        only — ZERO per-step host->device data transfers (pinned by
        tests/unit/test_resident_data.py via runtime.transfer_stats).

        steps_per_execution composes: full groups of `spe` steps run in
        one dispatch; a ragged tail (steps_per_epoch % spe) runs
        through a second executable with its own baked scan length, so
        a call never straddles an epoch boundary (the in-graph
        permutation is derived once per call).

        start_step (graftguard resume): skip the first `start_step`
        steps of the FIRST epoch by dropping whole executable calls and
        re-basing the position arithmetic — in-graph batch indices
        continue the interrupted epoch's permutation exactly. Dispatch
        is the abort granularity, so checkpointed positions are always
        call-aligned; a foreign (unaligned) position falls back to
        replaying the epoch from 0 with a warning.
        """
        weighted = resident.kind == "xyw"
        steps = resident.steps_per_epoch
        if steps_per_epoch is not None:
            steps = min(steps, int(steps_per_epoch))
        spe = min(self.steps_per_execution, steps)
        n_groups, leftover = divmod(steps, spe)
        start = int(start_step)
        if start and (start % spe or start >= steps):
            logger.warning(
                "Resident resume position step_in_epoch=%d does not "
                "sit on a dispatch boundary (steps_per_execution=%d, "
                "steps_per_epoch=%d); replaying the epoch from its "
                "start instead.", start, spe, steps)
            start = 0
        # Each executable build re-points self._train_scalar_unmasked
        # at a fresh set (populated at trace time); keep a reference to
        # every build's set so the first-step guard below sees whichever
        # executable traced first.
        scalar_sets = []
        run_group = run_tail = None
        if n_groups:
            run_group = self._make_resident_run(spe, steps, resident,
                                                weighted)
            scalar_sets.append(self._train_scalar_unmasked)
        if leftover:
            run_tail = self._make_resident_run(leftover, steps,
                                               resident, weighted)
            scalar_sets.append(self._train_scalar_unmasked)
        if warm_start:
            # AOT-compile both executables before the loop: structs
            # mirror (state, data, base_step, epoch_idx), so the first
            # epoch's first dispatch is the finished executable.
            struct = lambda tree: jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), tree)
            scalar_i32 = jax.ShapeDtypeStruct((), jnp.int32)
            for run in (run_group, run_tail):
                if run is not None:
                    run.warm(struct(self.state), struct(resident.data),
                             scalar_i32, scalar_i32)
        # The epoch index lives on device and is advanced there (one
        # tiny add per epoch, no transfer); it starts from the source
        # dataset's `_epoch` counter so shuffled order matches the
        # host path exactly (fit's shape-inference peek has already
        # consumed one epoch of that counter) and keeps advancing it,
        # so a later host-path fit on the same dataset resumes the
        # shuffle stream where this one left off.
        src = resident.source
        ep_idx = jnp.asarray(getattr(src, "_epoch", 0), dtype=jnp.int32)
        if self._mesh is not None:
            ep_idx = jax.device_put(ep_idx,
                                    sharding_lib.replicated(self._mesh))
        data = resident.data
        first_epoch = True
        # Host step mirror for current_data_state / graftchaos: one
        # boundary sync here, advanced by the host count per epoch.
        host_base = int(self.state.step)

        for epoch in range(initial_epoch, epochs):
            epoch_start = start if epoch == initial_epoch else 0
            self._data_progress = {
                "epoch": epoch,
                "epoch_base": host_base - epoch_start,
                # The counter value this epoch's permutation draws
                # from — read BEFORE the += 1 below.
                "dataset_epoch": int(getattr(src, "_epoch", 0)),
                "steps_per_epoch": steps,
                "data_seed": getattr(src, "seed", None),
            }
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            if not first_epoch:
                ep_idx = ep_idx + 1
            first_epoch = False
            if hasattr(src, "_epoch"):
                src._epoch += 1
            # Position arithmetic is relative to the step counter at
            # EPOCH entry (a mid-epoch abort leaves step partially
            # advanced; re-basing keeps the next epoch's positions at
            # 0..steps-1). On a mid-epoch resume the restored counter
            # is `epoch_start` PAST the epoch's base, so subtract it —
            # the in-graph `(step - base) % steps` then lands on the
            # interrupted permutation position. A REAL copy: each call
            # donates the state (and with it the live step buffer).
            base = jnp.array(self.state.step, copy=True)
            if epoch_start:
                base = base - epoch_start
            if self._mesh is not None:
                base = jax.device_put(
                    base, sharding_lib.replicated(self._mesh))
            step_logs = []
            count = 0
            t0 = time.time()
            # Same graftsan step label as _fit_epochs: executable calls
            # only between here and _post_epoch_logs' "boundary".
            runtime.set_phase("step")
            # graftscope: same span contract as _fit_epochs — the
            # resident loop has no data wait (batches are drawn
            # in-graph), so each call is one train_step span whose
            # body is all dispatch.
            step_section = spans_lib.begin("step")
            calls = [(run_group, spe)] * n_groups
            if leftover:
                calls.append((run_tail, leftover))
            if epoch_start:
                # Aligned by the guard above: drop the already-run
                # whole calls; the base re-basing keeps the remaining
                # calls' in-graph positions continuous.
                calls = calls[epoch_start // spe:]
            for run, n_steps in calls:
                if self._abort_epoch:
                    break
                if self._chaos is not None:
                    self._chaos.pre_dispatch(host_base + count, n_steps)
                if count == 0 and epoch == initial_epoch:
                    self._maybe_capture_step_flops(
                        run, n_steps, self.state, data, base, ep_idx)
                train_handle = spans_lib.begin("train_step")
                with spans_lib.span("dispatch"):
                    self.state, logs = run(self.state, data, base,
                                           ep_idx)
                spans_lib.end(train_handle)
                if "_batch_weight" in logs:
                    if n_steps > 1:
                        # Same group-entry semantics as the
                        # steps_per_execution path (_fit_epochs).
                        logs = dict(logs)
                        logs["_steps"] = n_steps
                    step_logs.append(logs)
                else:
                    step_logs.extend([logs] * n_steps)
                if (count == 0 and epoch == initial_epoch
                        and any(scalar_sets)):
                    raise ValueError(
                        "Custom metrics {} return a scalar and cannot "
                        "apply sample_weight. Give them a mask-aware "
                        "signature fn(outputs, y, mask=...) or return "
                        "per-example values.".format(
                            sorted(set().union(*scalar_sets))))
                count += n_steps
                # graftwatch beat + graftguard resume probe.
                self._note_dispatch_done()
            spans_lib.end(step_section)
            host_base += count
            if not (self._abort_epoch and count == 0):
                self._post_epoch_logs(step_logs, count,
                                      count * resident.batch_size, t0,
                                      epoch, validation_data,
                                      batch_size, callbacks, history,
                                      verbose, prefetch)
            if self.stop_training:
                break

    def _post_epoch_logs(self, step_logs, count, examples, t0, epoch,
                         validation_data, batch_size, callbacks, history,
                         verbose, prefetch):
        """Epoch-end: aggregate step logs, validate, notify callbacks.

        The aggregation math runs ON DEVICE and the result is ONE
        pytree of scalars, fetched with a single coalesced
        `runtime.device_fetch` — one tunnel round trip per epoch
        instead of one per metric (the round-3 regression this used to
        be: N x float() at ~66ms apiece on the tunneled chip). With
        `async_logging` (fit's default) even that one fetch moves to
        the background reader thread; callbacks get a `LazyLogs` that
        resolves only when something actually reads a metric value,
        and the history append is deferred to fit's exit barrier.
        """
        # Epoch boundary: host syncs (the coalesced fetch, validation,
        # verbose printing) are sanctioned here — relabel the thread so
        # graftsan doesn't count them against the step loop.
        runtime.set_phase("boundary")
        # graftwatch: boundary host work (validation, checkpoint, the
        # coalesced fetch) is progress too — beat so a long validation
        # pass isn't mistaken for a stalled step loop.
        watch_lib.heartbeat()
        # graftscope: the boundary host work (aggregation, validation,
        # callbacks, sentinel) is one "boundary" span, ended right
        # before the method returns.
        boundary_handle = spans_lib.begin("boundary")
        if step_logs and "_batch_weight" in step_logs[0]:
            # Weighted fit: epoch metrics re-weight each batch's
            # weighted mean by that batch's weight sum (exact over
            # the epoch); the loss keeps Keras sum-over-batch-size
            # semantics (plain mean over equal-size batches).
            ws = jnp.stack([l["_batch_weight"] for l in step_logs])
            total_w = jnp.maximum(jnp.sum(ws), 1e-9)
            # Per-entry step counts: a steps_per_execution group entry
            # carries the mean over `spe` steps and must weigh `spe`
            # times a leftover single batch in the per-step loss mean
            # (mirrors the extend([logs]*spe) semantics of the
            # unweighted path).
            ns = jnp.asarray([float(l.get("_steps", 1))
                              for l in step_logs])
            dev_logs = {}
            for k in step_logs[0]:
                if k in ("_batch_weight", "_steps"):
                    continue
                vals = jnp.stack([l[k] for l in step_logs])
                if k == "loss":
                    dev_logs[k] = jnp.sum(vals * ns) / jnp.sum(ns)
                else:
                    dev_logs[k] = jnp.sum(vals * ws) / total_w
        elif step_logs:
            dev_logs = dict(jax.tree_util.tree_map(
                lambda *xs: jnp.mean(jnp.stack(xs)), *step_logs))
        else:
            dev_logs = {}
        elapsed = max(time.time() - t0, 1e-9)
        host_logs = {"steps_per_sec": count / elapsed}
        _emit_runtime_metrics(count, examples, elapsed)
        _emit_telemetry_epoch(count, examples, elapsed)

        if validation_data is not None and self._abort_epoch:
            # Preemption abort: the eviction grace window is for the
            # checkpoint (PreemptionCheckpoint saves in on_epoch_end,
            # below) — a full validation pass here could eat it.
            validation_data = None
        if validation_data is not None:
            # Keras-style (x, y) or (x, y, sample_weight).
            if len(validation_data) == 3:
                val_x, val_y, val_sw = validation_data
            else:
                val_x, val_y = validation_data
                val_sw = None
            val_logs = self.evaluate(val_x, val_y,
                                     batch_size=batch_size,
                                     verbose=False,
                                     prefetch=prefetch,
                                     sample_weight=val_sw)
            host_logs.update(
                {"val_" + k: v for k, v in val_logs.items()})

        # The SAME device computation feeds both paths — sync vs async
        # differ only in who calls device_fetch and when, so the values
        # are bit-identical (pinned by test_async_host_loop). History
        # append is DEFERRED to fit's exit barrier either way:
        # appending here on the async path would force the fetch and
        # stall the loop, and the deferred snapshot (taken BEFORE the
        # callbacks run) preserves the existing contract that callback
        # mutations to `logs` are not recorded in history.
        if dev_logs and self._async_logging:
            future = self._metric_reader.submit(dev_logs)
            logs = async_logs_lib.LazyLogs(
                future, device_keys=tuple(dev_logs), host_items=host_logs)
            self._pending_history.append(
                (future, tuple(dev_logs), dict(host_logs)))
        else:
            if dev_logs:
                fetched = runtime.device_fetch(dev_logs)
                logs = {k: float(v) for k, v in fetched.items()}
                logs.update(host_logs)
            else:
                logs = dict(host_logs)
            self._pending_history.append((None, (), dict(logs)))
        if verbose and jax.process_index() == 0:
            # Progress output needs the values; this resolves the
            # future — still ONE coalesced fetch for the interval, just
            # no longer an off-thread one.
            logger.info("epoch %d: %s", epoch, {
                k: round(v, 4) for k, v in logs.items()})
        for cb in callbacks:
            cb.on_epoch_end(epoch, logs)

        # Retrace sentinel: the baseline snapshots at the end of the
        # FIRST completed epoch (its compiles are legitimate — step
        # executables, validation's eval step, callback one-offs);
        # any later epoch that moved the trace/compile counters is the
        # regression the counted invariant exists to catch (ragged
        # tails, input dtype drift, a new decode shape). Checked after
        # the callbacks so epoch-scoped callback compiles are counted
        # against the epoch that ran them.
        stats = runtime.compile_stats()
        snapshot = (stats["n_traces"], stats["n_compiles"])
        baseline = getattr(self, "_retrace_baseline", None)
        if baseline is None:
            self._retrace_baseline = snapshot
        elif snapshot != baseline:
            # Re-base first: one event, one report (and a "raise" that
            # gets caught shouldn't re-raise every later epoch).
            self._retrace_baseline = snapshot
            msg = ("Steady-state retrace: epoch {} performed {} new "
                   "trace(s) / {} new compile(s) after the first "
                   "epoch's warm-up. Ragged tail batches, input dtype "
                   "drift and per-epoch callback compiles are the "
                   "usual causes; runtime.compile_stats() has the "
                   "running census.".format(
                       epoch, snapshot[0] - baseline[0],
                       snapshot[1] - baseline[1]))
            policy = getattr(self, "_on_retrace", "warn")
            if policy == "raise":
                raise runtime.RetraceWarning(msg)
            if policy == "warn":
                warnings.warn(runtime.RetraceWarning(msg))
        # One completed epoch: graftsan's retrace check (GS002) arms
        # only after the warm-up epoch has finished, mirroring the
        # sentinel's own baseline timing above.
        runtime.notify_epoch(epoch)
        spans_lib.end(boundary_handle)

    def summary(self, print_fn=None):
        """Keras `model.summary()` parity: per-top-level-module
        parameter counts plus totals (params and, when present, extra
        variable collections like BatchNorm stats). Returns the text.
        Requires a built model (fit() or build())."""
        if self.state is None:
            raise RuntimeError("Model is not built; call fit() first or "
                               "build() with a sample batch.")

        def count(tree):
            return sum(int(np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(tree))

        def nbytes(tree):
            return sum(int(np.prod(l.shape)) * l.dtype.itemsize
                       for l in jax.tree_util.tree_leaves(tree))

        params = self.state.params
        rows = []
        if isinstance(params, dict):
            for name in sorted(params):
                rows.append((name, count(params[name])))
        total = count(params)
        width = max([len(n) for n, _ in rows]
                    + [len("Extra vars (e.g. BN stats)")])
        lines = ["{:<{w}}  {:>14}".format("Module", "Params", w=width),
                 "-" * (width + 16)]
        for name, n in rows:
            lines.append("{:<{w}}  {:>14,}".format(name, n, w=width))
        lines.append("-" * (width + 16))
        lines.append("{:<{w}}  {:>14,}".format("Total params", total,
                                               w=width))
        lines.append("{:<{w}}  {:>14}".format(
            "Param bytes", "{:,}".format(nbytes(params)), w=width))
        extra = count(self.state.extra_vars)
        if extra:
            lines.append("{:<{w}}  {:>14,}".format(
                "Extra vars (e.g. BN stats)", extra, w=width))
        text = "\n".join(lines)
        (print_fn or (lambda t: logger.info("%s", t)))(text)
        return text

    @property
    def ema_params(self):
        """The EMA shadow parameters (requires `ema_decay=`)."""
        if self.ema_decay is None:
            raise RuntimeError(
                "No EMA is tracked; construct Trainer(ema_decay=...).")
        if self.state is None:
            raise RuntimeError("Model is not built; call fit() first.")
        opt_state = self.state.opt_state
        if self.gradient_accumulation_steps > 1:
            opt_state = opt_state.inner_opt_state
        return opt_state[-1].ema

    def _eval_state(self, use_ema):
        if not use_ema:
            return self.state
        s = self.state
        return TrainState(s.step, self.ema_params, s.opt_state, s.rng,
                          s.extra_vars)

    def save_checkpoint(self, directory, use_async=False):
        """Saves the full train state under `<directory>/<step>` (local
        or gs://). Keras `model.save` parity at the state level; pair
        with `restore_checkpoint` or `fit(resume_from=...)`. With
        use_async=True the write happens on a background thread
        (checkpoint.wait_until_finished() blocks on it)."""
        from cloud_tpu.training import checkpoint as checkpoint_lib

        if self.state is None:
            raise RuntimeError("Model is not built; nothing to save.")
        return checkpoint_lib.save(directory, self.state,
                                   step=int(self.state.step),
                                   use_async=use_async)

    def restore_checkpoint(self, directory, sample_x, step=None):
        """Builds congruent state from `sample_x`, then restores the
        checkpoint into it (shardings respected)."""
        from cloud_tpu.training import checkpoint as checkpoint_lib

        self.build(sample_x)
        self.state = checkpoint_lib.restore(directory, self.state,
                                            step=step)
        return self.state

    @_env_watched
    @_env_telemetry
    @_env_sanitized
    def evaluate(self, x, y=None, batch_size=32, verbose=True,
                 steps=None, prefetch=2, use_ema=False,
                 sample_weight=None):
        """Returns exact example-weighted mean loss/metrics.

        Tail batches are padded by wrapping (never dropped) so shapes
        stay static for XLA, but padded duplicates are masked out inside
        the eval step and each batch is weighted by its real example
        count — metrics match a hand-computed mean over the dataset
        (Keras-exact), regardless of tail padding. Custom metrics may
        opt into the valid-mask via a `fn(outputs, y, mask=...)`
        signature; a custom metric that returns a scalar WITHOUT taking
        the mask raises on padded batches rather than silently folding
        duplicated rows into its mean.

        `steps` caps the batch loop; when unset, a dataset-level
        `steps_per_epoch` (e.g. GeneratorDataset over an unbounded
        stream) applies, mirroring fit(). `prefetch` is the device
        read-ahead depth (0 = synchronous), mirroring fit(); fit()
        forwards its own value to the per-epoch validation pass.

        `sample_weight`: optional [num_examples] per-example weights;
        every reported value becomes the weighted mean
        sum(v_i * w_i) / sum(w_i) over the dataset (weights compose
        with the tail-padding mask). Array inputs; works multi-process
        (the per-batch weight is summed in-graph over the global mask).
        """
        if self.state is None:
            raise RuntimeError("Model is not built; call fit() first or "
                               "build() with a sample batch.")
        if self._jit_eval_step is None:
            self._jit_eval_step = self._make_eval_step()
        if sample_weight is not None and not (
                hasattr(x, "shape") or isinstance(x, (dict, list, tuple))):
            raise ValueError(
                "sample_weight= needs raw array inputs; pre-built "
                "datasets carry their own weights via "
                "ArrayDataset(sample_weight=...).")
        ds_kwargs = {}
        if sample_weight is not None:
            ds_kwargs["sample_weight"] = sample_weight
        dataset = data_lib.as_dataset(x, y, batch_size=batch_size,
                                      drop_remainder=False, **ds_kwargs)
        if (sample_weight is not None
                and not isinstance(dataset, data_lib.ArrayDataset)):
            raise ValueError(
                "sample_weight= needs array inputs (wrap the dataset "
                "in ArrayDataset(sample_weight=...) instead).")
        weighted_eval = (isinstance(dataset, data_lib.ArrayDataset)
                         and dataset.sample_weight is not None)
        if steps is None:
            steps = getattr(dataset, "steps_per_epoch", None)
        num_examples = getattr(dataset, "num_examples", None)
        global_bs = getattr(dataset, "batch_size", None)
        process_count = jax.process_count()
        process_index = jax.process_index()
        def masked_batches():
            """(aggregation_weight, padded, (x, y, mask)) per batch —
            `mask` is the valid-row mask times any per-example weights
            (the eval step's masked means are then weighted means),
            and `aggregation_weight` is the batch's share of the final
            example-weighted (or sample-weighted) average."""
            for i, batch in enumerate(self._epoch_batches(dataset)):
                if steps is not None and i >= steps:
                    break
                # Same unpacking the train step applies: a 3-sequence
                # is (x, y, sample_weight), a 2-sequence is (x, y);
                # anything else is unlabeled input.
                wb = None
                if isinstance(batch, (tuple, list)) and len(batch) == 3:
                    xb, yb, wb = batch
                elif isinstance(batch, (tuple, list)) and len(batch) == 2:
                    xb, yb = batch
                else:
                    xb, yb = batch, None
                local_b = jax.tree_util.tree_leaves(xb)[0].shape[0]
                if num_examples is not None and global_bs is not None:
                    # ArrayDataset pads the tail by wrapping: only the
                    # first `real` rows of the global batch are fresh.
                    real = min(global_bs, num_examples - i * global_bs)
                else:
                    # Arbitrary iterables yield their own (unpadded)
                    # batches.
                    real = local_b * process_count
                # This process holds global rows
                # [offset, offset + local_b).
                offset = (process_index * local_b
                          if process_count > 1 else 0)
                mask = ((np.arange(local_b) + offset) < real).astype(
                    np.float32)
                padded = real < local_b * process_count
                if wb is not None:
                    mask = mask * np.asarray(wb, np.float32)
                    agg = float(mask.sum())
                else:
                    agg = float(real)
                yield agg, padded, (xb, yb, mask)

        feeder = data_lib.prefetch_to_device(
            masked_batches(), size=prefetch,
            feed=lambda item: (item[0], item[1], self._feed(item[2])))
        eval_state = self._eval_state(use_ema)
        totals, weight = {}, 0.0
        for agg, padded, fed in feeder:
            logs = dict(self._jit_eval_step(eval_state, fed))
            # graftwatch: an eval batch is liveness (but not a train
            # step — it beats without advancing the step census).
            watch_lib.heartbeat()
            batch_w = logs.pop("_batch_weight")
            if weighted_eval:
                # The host-side `agg` summed only this process's local
                # mask shard; the in-graph sum covers the GLOBAL mask,
                # making weighted evaluate exact on pods (round-3 gap:
                # this path used to raise NotImplementedError under
                # process_count > 1). Stays a device scalar — no sync.
                agg = batch_w
            # Padding only ever happens on the ArrayDataset path
            # (num_examples known, tail wrapped); datasets that just
            # yield a short final batch (e.g. shard tails) are short,
            # not padded — their mask is all-ones and every metric is
            # exact. A scalar metric that can't take the mask is also
            # wrong under sample weights, padded or not.
            if ((padded or weighted_eval)
                    and self._scalar_unmasked_metrics):
                raise ValueError(
                    "Custom metrics {} return a scalar and cannot be "
                    "masked, but this evaluation needs per-row "
                    "weighting ({}). Give the metric a mask-aware "
                    "signature fn(outputs, y, mask=...) (weight rows "
                    "by mask), or return per-example values "
                    "instead.".format(
                        sorted(self._scalar_unmasked_metrics),
                        "sample_weight" if weighted_eval
                        else "padded tail batch"))
            weight += agg
            for k, v in logs.items():
                # Device-side accumulation: no host sync per batch (one
                # tunnel round-trip per eval batch otherwise); the
                # coalesced fetch below is the only barrier.
                totals[k] = totals.get(k, 0.0) + v * agg
        # ONE coalesced fetch for the whole evaluation: the weight and
        # every metric total come back in a single device_get (counted
        # once in transfer_stats()["d2h_fetches"]) — this used to be
        # N+1 float() round trips at ~66ms apiece on the tunneled chip.
        weight, totals = runtime.device_fetch((weight, totals))
        weight = float(weight)
        if weight == 0.0:
            if weighted_eval:
                raise ValueError(
                    "evaluate(): total sample_weight is zero — no "
                    "example carries weight, so no mean exists.")
            raise ValueError("evaluate() received an empty dataset.")
        logs = {k: float(v) / weight for k, v in totals.items()}
        if verbose and jax.process_index() == 0:
            logger.info("evaluate: %s", {
                k: round(v, 4) for k, v in logs.items()})
        return logs

    def _make_predict_step(self):
        eval_kwargs = self.eval_kwargs

        def predict_step(state, xb):
            return self._apply(state.params, xb,
                               extra_vars=state.extra_vars, **eval_kwargs)

        if self._mesh is None:
            return runtime.instrumented_jit(predict_step)
        return runtime.instrumented_jit(
            predict_step,
            in_shardings=(self._state_sharding,
                          sharding_lib.batch_sharding(self._mesh)))

    def predict(self, x, batch_size=32, prefetch=2, use_ema=False):
        """Returns stacked model outputs for `x`.

        Jitted and prefetched like fit/evaluate: batches stream to
        device `prefetch` ahead, outputs stay on device until one
        gather at the end.
        """
        if self.state is None:
            raise RuntimeError("Model is not built; call fit() first.")
        if self._jit_predict_step is None:
            self._jit_predict_step = self._make_predict_step()
        dataset = data_lib.as_dataset(x, None, batch_size=batch_size,
                                      drop_remainder=False)
        feeder = data_lib.prefetch_to_device(
            iter(dataset), size=prefetch, feed=self._feed)
        # One-behind gather: batch i's output is pulled to host while
        # batch i+1 computes — transfer overlaps compute without ever
        # holding more than two batches of outputs in HBM. Outputs are
        # arbitrary pytrees (a tuple/dict-returning model, e.g. MoEMLP's
        # (out, aux)): transfer and concatenation are per leaf, and the
        # result keeps the model's output structure.
        outs = []
        pending = None
        predict_state = self._eval_state(use_ema)
        for xb in feeder:
            out = self._jit_predict_step(predict_state, xb)
            if pending is not None:
                outs.append(runtime.device_fetch(pending))
            pending = out
        if pending is not None:
            outs.append(runtime.device_fetch(pending))
        n = jax.tree_util.tree_leaves(x)[0].shape[0]

        def join(*leaves):
            # A 0-d leaf (e.g. MoEMLP's scalar aux loss) is per-BATCH,
            # not per-example: stack into [num_batches] instead of
            # concatenating along a batch axis it doesn't have.
            if np.ndim(leaves[0]) == 0:
                return np.stack(leaves)
            return np.concatenate(leaves, axis=0)[:n]

        return jax.tree_util.tree_map(join, *outs)
