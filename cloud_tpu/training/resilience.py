"""graftguard: elastic, preemption-native training.

ROADMAP item 4's recovery half. graftwatch (PR 7) turns a silent stall
into a typed `runtime.BackendUnavailable` within a bounded deadline;
this module is what finally CATCHES it — plus the rest of the fault
taxonomy a preemptible-capacity fleet actually produces (SIGTERM-style
preemptions, torn checkpoints, transient input stalls, non-finite
losses) — and turns "the job died at 3am" into "the job backed off,
rolled back to the last good checkpoint, and re-entered through the
warm compile cache".

The supervising loop (`resilient_fit`, surfaced as
`Trainer.fit(resume="auto")`):

1. runs `Trainer._fit_impl` with `resume_from` pointed at a checkpoint
   directory and an `AutoCheckpoint` callback stamping the resumable
   data-stream position (`(epoch, step_in_epoch, dataset_epoch,
   data_seed)`) into every save's metadata sidecar;
2. on a typed fault: records it (module stats + graftscope counters +
   a "graftguard" JSONL job event), takes a best-effort rescue
   checkpoint of the live state (the fault taxonomy raises BETWEEN
   dispatches, so the state is a consistent post-step snapshot),
   quarantines the offending checkpoint instead when the fault IS the
   checkpoint (`CheckpointCorrupt` → fall back to the previous one),
   and skips the rescue on `NaNLoss` (the live state is the non-finite
   one — resume from the last FINITE checkpoint, with a fresh
   data-order rng so the same batch sequence doesn't march back into
   the same NaN);
3. backs off (capped exponential + jitter, budgeted by
   `CLOUD_TPU_RETRIES`) and re-enters. Re-entry restores the latest
   checkpoint, re-bases the shuffle stream to the saved mid-epoch
   position (bit-identical continuation — see
   `Trainer._apply_data_state`), re-arms graftwatch's startup deadline
   (`watch.notify_reentry`), and reuses the still-warm executables
   (`_train_step_cache` / `_resident_run_cache`), so the resumed run
   pays restore + dispatch — not a recompile. The first completed
   dispatch after re-entry reports `resume_latency` and the
   new-traces/new-compiles delta (the zero-new-compiles invariant CI
   asserts).

Knobs: `CLOUD_TPU_RETRIES` (retry budget, default 3),
`CLOUD_TPU_RETRY_BACKOFF` (base seconds, default 1.0),
`CLOUD_TPU_RETRY_BACKOFF_CAP` (default 30.0), `CLOUD_TPU_RESUME_DIR`
(checkpoint directory when the caller gives none). The chaos harness
that exercises all of this deterministically lives in
`cloud_tpu/analysis/chaos.py` (`CLOUD_TPU_CHAOS`).
"""

import logging
import os
import random
import sys
import time

from cloud_tpu.parallel import runtime
from cloud_tpu.training import callbacks as callbacks_lib

logger = logging.getLogger("cloud_tpu")


# --------------------------------------------------------------------------
# Typed fault taxonomy
# --------------------------------------------------------------------------


class TrainingFault(RuntimeError):
    """Base of graftguard's fault taxonomy: an interruption the
    supervising retry loop knows how to answer (checkpoint, back off,
    resume) — as opposed to a programming error, which propagates."""

    fault_kind = "training_fault"


class Preemption(TrainingFault):
    """The host is being reclaimed (spot/preemptible capacity) — the
    SIGTERM-grace-window class of interruption. Checkpoint and resume
    on a replacement."""

    fault_kind = "preemption"


class CheckpointCorrupt(TrainingFault):
    """A checkpoint failed its content digest or would not deserialize
    — a torn write, a truncated object, bit rot. graftguard quarantines
    the step and falls back to the previous checkpoint."""

    fault_kind = "checkpoint_corrupt"

    def __init__(self, message, path=None, step=None):
        super().__init__(message)
        self.path = path
        self.step = step


class DataStall(TrainingFault):
    """The input pipeline stopped producing (transient fetch error,
    wedged remote read). Usually transient: retry re-enters the same
    position."""

    fault_kind = "data_stall"


class NaNLoss(TrainingFault):
    """The monitored loss went non-finite (`TerminateOnNaN`
    rollback=True). graftguard resumes from the last FINITE checkpoint
    with a fresh data-order rng — same params, different batch
    sequence."""

    fault_kind = "nan_loss"

    def __init__(self, message, epoch=None, monitor=None, value=None):
        super().__init__(message)
        self.epoch = epoch
        self.monitor = monitor
        self.value = value


#: Everything the supervising loop catches. `BackendUnavailable` is
#: runtime's (the watchdog raised it long before graftguard existed);
#: it carries its own `fault_kind` class attr so classification is
#: uniform.
FAULT_TYPES = (TrainingFault, runtime.BackendUnavailable)


def fault_kind(exc):
    """The taxonomy label for a caught fault ("preemption",
    "backend_unavailable", ...), or "unknown" for anything else."""
    return getattr(type(exc), "fault_kind", "unknown")


# --------------------------------------------------------------------------
# Stats / telemetry / events
# --------------------------------------------------------------------------

_STATS_ZERO = {
    "faults": 0,
    "retries": 0,
    "rollbacks": 0,
    "giveups": 0,
    "resumes": 0,
    "last_fault": None,
    "last_resume_latency_seconds": None,
    "last_resume_new_traces": None,
    "last_resume_new_compiles": None,
}
_stats = dict(_STATS_ZERO)


def guard_stats():
    """Snapshot of the process-wide graftguard counters — the
    telemetry-free introspection point (tests, bench records)."""
    return dict(_stats)


def reset_guard_stats():
    """Zeroes the counters (test isolation)."""
    _stats.update(_STATS_ZERO)


class _GuardScope:
    """Snapshot/delta view over the process-global guard counters —
    see `guard_scope()`."""

    _COUNTERS = ("faults", "retries", "rollbacks", "giveups", "resumes")
    _LAST_RESUME = ("last_resume_latency_seconds",
                    "last_resume_new_traces",
                    "last_resume_new_compiles")

    def __init__(self):
        self._base = None
        self._final = None

    def __enter__(self):
        self._base = guard_stats()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._final = guard_stats()
        return False

    def stats(self):
        """The delta accrued inside the scope: integer counters as
        differences; `last_fault` / `last_resume_*` only when this
        scope saw a fault / resume (else None — a previous scope's
        leftovers never leak in). Valid mid-scope (live delta) and
        after exit (frozen at `__exit__`)."""
        if self._base is None:
            raise RuntimeError("guard_scope stats read before entry.")
        end = self._final if self._final is not None else guard_stats()
        out = {key: end[key] - self._base[key] for key in self._COUNTERS}
        out["last_fault"] = end["last_fault"] if out["faults"] else None
        for key in self._LAST_RESUME:
            out[key] = end[key] if out["resumes"] else None
        return out


def guard_scope():
    """Context manager scoping `guard_stats()` to one supervised run.

    The module-global counters are process-wide by design (telemetry,
    bench records); anything running MANY supervised fits in one
    process — a graftsweep trial, a test — needs per-run attribution.
    `with guard_scope() as guard:` snapshots on entry and `guard.stats()`
    returns only what accrued inside the scope, so trial K's faults
    never bleed into trial K+1's census. Nestable (each scope deltas
    independently); never resets the globals."""
    return _GuardScope()


def _registry():
    # graftscope is optional: touch it only when the process already
    # imported it AND a Telemetry is active (same discipline as watch).
    telemetry = sys.modules.get("cloud_tpu.monitoring.telemetry")
    if telemetry is None:
        return None
    try:
        tele = telemetry.get()
        if tele is None or not tele.active:
            return None
        return tele.registry
    except Exception:
        return None


def _count(name, delta=1):
    reg = _registry()
    if reg is None:
        return
    try:
        reg.counter(name).inc(delta)
    except Exception:
        logger.debug("graftguard: counter %s export failed", name,
                     exc_info=True)


def _gauge(name, value):
    reg = _registry()
    if reg is None:
        return
    try:
        reg.gauge(name).set(value)
    except Exception:
        logger.debug("graftguard: gauge %s export failed", name,
                     exc_info=True)


def _log_event(payload):
    # JSONL job event (no-op unless CLOUD_TPU_EVENT_LOG is set): the
    # fleet-side record of every fault/retry/resume, same stream the
    # watchdog and chaos harness write to.
    try:
        from cloud_tpu.utils import events

        events.log_job_event("graftguard", payload)
    except Exception:
        logger.debug("graftguard: job event export failed", exc_info=True)


# --------------------------------------------------------------------------
# Backoff
# --------------------------------------------------------------------------


def _env_float(name, default):
    value = os.environ.get(name)
    if value is None:
        return default
    try:
        return float(value)
    except ValueError:
        logger.warning("Ignoring malformed %s=%r.", name, value)
        return default


def backoff_delay(attempt, base=1.0, cap=30.0, rng=None):
    """Capped exponential backoff with jitter, seconds.

    attempt 0 → ~base, attempt k → min(cap, base * 2**k), each scaled
    by a uniform [0.5, 1.0) jitter so a preempted fleet doesn't
    thunder back in lockstep. Pass an explicit `random.Random` for
    deterministic tests.
    """
    if rng is None:
        rng = random
    # 2.0**attempt overflows a float past attempt 1023; any exponent
    # beyond 64 is already astronomically over every sane cap.
    raw = min(float(cap), float(base) * (2.0 ** min(int(attempt), 64)))
    return raw * (0.5 + 0.5 * rng.random())


# --------------------------------------------------------------------------
# Resume probe: latency + the zero-new-compiles invariant
# --------------------------------------------------------------------------


class _ResumeProbe:
    """Armed by the retry loop right before re-entry; the fit loop
    fires it after the FIRST completed dispatch. Measures wall-clock
    resume latency (restore + rebuild + first step) and the compile
    delta since the fault — a warm re-entry reports new_compiles == 0
    (the retrace sentinel's invariant, asserted by the chaos-smoke CI
    job)."""

    def __init__(self, kind, attempt):
        self.kind = kind
        self.attempt = attempt
        self.t0 = time.monotonic()
        stats = runtime.compile_stats()
        self.baseline = (stats["n_traces"], stats["n_compiles"])

    def first_step(self):
        latency = time.monotonic() - self.t0
        stats = runtime.compile_stats()
        new_traces = stats["n_traces"] - self.baseline[0]
        new_compiles = stats["n_compiles"] - self.baseline[1]
        _stats["resumes"] += 1
        _stats["last_resume_latency_seconds"] = latency
        _stats["last_resume_new_traces"] = new_traces
        _stats["last_resume_new_compiles"] = new_compiles
        _gauge("cloud_tpu_resume_latency_seconds", latency)
        _log_event({
            "event": "resumed",
            "fault": self.kind,
            "attempt": self.attempt,
            "resume_latency_seconds": round(latency, 6),
            "new_traces": new_traces,
            "new_compiles": new_compiles,
        })
        logger.info(
            "graftguard: resumed after %s in %.3fs "
            "(new traces=%d, new compiles=%d).",
            self.kind, latency, new_traces, new_compiles)


# --------------------------------------------------------------------------
# Auto-checkpoint callback
# --------------------------------------------------------------------------


class AutoCheckpoint(callbacks_lib.Callback):
    """Epoch-granular graftguard checkpoints with the resumable
    data-stream position stamped into the metadata sidecar.

    Unlike `ModelCheckpoint` this is unconditional (no monitor/mode):
    its job is recovery, not best-model selection, so every epoch end
    writes `<directory>/<global step>` plus `data_state` metadata.
    Earlier steps are kept — `CheckpointCorrupt` fallback needs a
    previous checkpoint to fall back TO.
    """

    def __init__(self, directory, use_async=False):
        self.directory = directory
        self.use_async = bool(use_async)

    def on_epoch_end(self, epoch, logs):
        trainer = self.trainer
        if trainer is None or trainer.state is None:
            return
        from cloud_tpu.training import checkpoint as checkpoint_lib

        checkpoint_lib.save(
            self.directory, trainer.state,
            step=int(trainer.state.step),
            use_async=self.use_async,
            data_state=trainer.current_data_state())

    def on_train_end(self, history):
        if self.use_async:
            from cloud_tpu.training import checkpoint as checkpoint_lib

            checkpoint_lib.wait_until_finished()


# --------------------------------------------------------------------------
# The supervising retry loop
# --------------------------------------------------------------------------


def _rescue_save(trainer, directory):
    """Best-effort checkpoint of the live state at fault time.

    The taxonomy raises between dispatches, so `trainer.state` is a
    consistent post-step snapshot — saving it means resume replays
    nothing. But an async-raised `BackendUnavailable` can land
    anywhere (donated buffers, a wedged device), so failure here is
    expected and fine: resume falls back to the last periodic
    checkpoint.
    """
    state = getattr(trainer, "state", None)
    if state is None:
        return None
    from cloud_tpu.training import checkpoint as checkpoint_lib

    try:
        step = int(state.step)
        path = checkpoint_lib.save(
            directory, state, step=step,
            data_state=trainer.current_data_state())
        _log_event({"event": "rescue_checkpoint", "step": step,
                    "path": str(path)})
        logger.info("graftguard: rescue checkpoint at step %d -> %s.",
                    step, path)
        return path
    except Exception:
        logger.warning(
            "graftguard: rescue checkpoint failed; resume will fall "
            "back to the last periodic checkpoint.", exc_info=True)
        return None


def resilient_fit(trainer, directory=None, retries=None,
                  backoff_base=None, backoff_cap=None, rng=None,
                  **fit_kwargs):
    """Runs `trainer._fit_impl(**fit_kwargs)` under graftguard.

    This is what `Trainer.fit(resume="auto")` delegates to. Typed
    faults (`FAULT_TYPES`) are caught, answered (rescue checkpoint /
    quarantine / fresh data rng — see the module docstring), and
    retried with capped exponential backoff until the budget is
    exhausted, at which point the LAST fault re-raises so outer
    handlers still see the typed error.

    Args:
        trainer: The `Trainer`.
        directory: Checkpoint root. Defaults to `resume_from` in
            `fit_kwargs`, then `CLOUD_TPU_RESUME_DIR`, then
            `./graftguard_ckpt`.
        retries: Retry budget; default `CLOUD_TPU_RETRIES` (3).
        backoff_base / backoff_cap: Backoff shape, seconds; defaults
            `CLOUD_TPU_RETRY_BACKOFF` (1.0) /
            `CLOUD_TPU_RETRY_BACKOFF_CAP` (30.0).
        rng: Optional `random.Random` for deterministic backoff jitter.
        **fit_kwargs: Forwarded to `Trainer._fit_impl`.

    Returns:
        The history dict, accumulated ACROSS attempts (each re-entry
        appends to the same dict, so the caller sees one continuous
        per-epoch stream).
    """
    from cloud_tpu.monitoring import watch as watch_lib
    from cloud_tpu.training import checkpoint as checkpoint_lib

    if retries is None:
        retries = int(_env_float("CLOUD_TPU_RETRIES", 3))
    if backoff_base is None:
        backoff_base = _env_float("CLOUD_TPU_RETRY_BACKOFF", 1.0)
    if backoff_cap is None:
        backoff_cap = _env_float("CLOUD_TPU_RETRY_BACKOFF_CAP", 30.0)

    fit_kwargs = dict(fit_kwargs)
    directory = (directory or fit_kwargs.get("resume_from")
                 or os.environ.get("CLOUD_TPU_RESUME_DIR"))
    if directory is None:
        directory = os.path.join(os.getcwd(), "graftguard_ckpt")
        logger.info(
            "graftguard: no checkpoint directory given "
            "(resume_from / CLOUD_TPU_RESUME_DIR); using %s.", directory)
    fit_kwargs["resume_from"] = directory

    callbacks = list(fit_kwargs.get("callbacks") or ())
    if not any(isinstance(cb, AutoCheckpoint) for cb in callbacks):
        callbacks.append(AutoCheckpoint(directory))
    fit_kwargs["callbacks"] = tuple(callbacks)

    # One history dict threaded through every attempt: _fit_impl's
    # finally-barrier materializes even a partial epoch's logs into it
    # before the fault propagates, so nothing is lost to a retry.
    history = fit_kwargs.pop("history", None)
    if history is None:
        history = {}
    data_seed = fit_kwargs.pop("data_seed", None)

    attempt = 0
    while True:
        # Re-arm graftwatch for this (re)entry: the startup deadline
        # (not the tight stall deadline) must cover restore + rebuild.
        # No-op when no watchdog is installed or on the first entry
        # (fit's own env_scope arms a fresh one).
        watch_lib.notify_reentry()
        try:
            trainer._fit_impl(history=history, data_seed=data_seed,
                              **fit_kwargs)
            return history
        except FAULT_TYPES as fault:
            kind = fault_kind(fault)
            _stats["faults"] += 1
            _stats["last_fault"] = kind
            _count("cloud_tpu_guard_faults_total")
            _log_event({"event": "fault", "fault": kind,
                        "attempt": attempt, "error": str(fault)})
            logger.warning("graftguard: caught %s fault: %s", kind, fault)

            if kind == "checkpoint_corrupt":
                # The checkpoint IS the fault: quarantine it so
                # latest_step falls back to the previous one. No
                # rescue save — the live state never restored.
                step = getattr(fault, "step", None)
                quarantined = (checkpoint_lib.quarantine(directory, step)
                               if step is not None else None)
                _stats["rollbacks"] += 1
                _count("cloud_tpu_guard_rollbacks_total")
                _log_event({"event": "rollback", "fault": kind,
                            "step": step,
                            "quarantined": quarantined and str(quarantined)})
            elif kind == "nan_loss":
                # The live state is the non-finite one: resume from
                # the last FINITE checkpoint, and re-seed the data
                # order so the replayed epoch draws a fresh batch
                # sequence instead of marching back into the NaN.
                data_seed = int(trainer.seed) + 1000003 * (attempt + 1)
                _stats["rollbacks"] += 1
                _count("cloud_tpu_guard_rollbacks_total")
                _log_event({"event": "rollback", "fault": kind,
                            "fresh_data_seed": data_seed})
                logger.warning(
                    "graftguard: non-finite loss; rolling back to the "
                    "last finite checkpoint with data_seed=%d.", data_seed)
            else:
                _rescue_save(trainer, directory)

            attempt += 1
            if attempt > retries:
                _stats["giveups"] += 1
                _log_event({"event": "giveup", "fault": kind,
                            "attempts": attempt, "budget": retries})
                logger.error(
                    "graftguard: retry budget exhausted "
                    "(%d attempts, budget %d); re-raising %s.",
                    attempt, retries, kind)
                raise
            delay = backoff_delay(attempt - 1, backoff_base,
                                  backoff_cap, rng=rng)
            _stats["retries"] += 1
            _count("cloud_tpu_guard_retries_total")
            _log_event({"event": "retry", "fault": kind,
                        "attempt": attempt, "budget": retries,
                        "backoff_seconds": round(delay, 3)})
            logger.warning(
                "graftguard: retry %d/%d after %s; backing off %.2fs "
                "then resuming from %s.", attempt, retries, kind, delay,
                directory)
            if delay > 0:
                time.sleep(delay)
            # Clock starts AFTER the backoff: resume latency measures
            # restore + rebuild + first dispatch, not the sleep.
            trainer._resume_probe = _ResumeProbe(kind, attempt)
