"""TensorBoard event-file writer, dependency-free.

The reference's metric return channel is TensorBoard event files on GCS
(reference tuner/tuner.py:532-560 parses them; tf_utils.py:27-51 builds
the DirectoryWatcher). This framework's primary channel is structured
JSONL (utils/metrics_watcher.py), but event-file COMPAT matters: any
TensorBoard instance pointed at a training dir should show the curves.
TensorFlow isn't a dependency here, so this module hand-encodes the two
tiny wire formats involved:

- TFRecord framing: little-endian uint64 length, masked crc32c of the
  length bytes, payload, masked crc32c of the payload. Masking is
  TensorFlow's ((crc >> 15 | crc << 17) + 0xa282ead8) % 2^32.
- `Event` protobuf (tensorflow/core/util/event.proto), scalar subset:
    Event { double wall_time=1; int64 step=2;
            oneof { string file_version=3; Summary summary=5; } }
    Summary { repeated Value value=1 }
    Value   { string tag=1; float simple_value=2 }

Only scalar summaries are emitted — exactly what per-epoch metrics and
the tuner's objective readback need. A matching minimal reader is
provided for tests and for the tuner-side parsing path.
"""

import json
import logging
import os
import socket
import struct
import sys
import time

from cloud_tpu.utils import storage

logger = logging.getLogger("cloud_tpu")

_CRC_TABLE = []
_WRITER_COUNT = 0


def _crc32c_table():
    # Castagnoli polynomial (reflected): 0x82F63B78.
    global _CRC_TABLE
    if not _CRC_TABLE:
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
            table.append(crc)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data):
    table = _crc32c_table()
    crc = 0xFFFFFFFF
    for byte in data:
        crc = table[(crc ^ byte) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data):
    crc = crc32c(data)
    return ((crc >> 15 | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def _varint(value):
    out = bytearray()
    value &= (1 << 64) - 1
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _key(field, wire_type):
    return _varint((field << 3) | wire_type)


def _len_delimited(field, payload):
    return _key(field, 2) + _varint(len(payload)) + payload


def _encode_value(tag, value):
    payload = (_len_delimited(1, tag.encode("utf-8"))
               + _key(2, 5) + struct.pack("<f", float(value)))
    return payload


def encode_scalar_event(step, scalars, wall_time=None):
    """Event proto bytes for {tag: float} scalars at `step`."""
    if wall_time is None:
        wall_time = time.time()
    summary = b"".join(
        _len_delimited(1, _encode_value(tag, value))
        for tag, value in scalars.items())
    return (_key(1, 1) + struct.pack("<d", wall_time)
            + _key(2, 0) + _varint(int(step))
            + _len_delimited(5, summary))


def encode_file_version(wall_time=None):
    if wall_time is None:
        wall_time = time.time()
    return (_key(1, 1) + struct.pack("<d", wall_time)
            + _len_delimited(3, b"brain.Event:2"))


def _frame(payload):
    header = struct.pack("<Q", len(payload))
    return (header + struct.pack("<I", _masked_crc(header))
            + payload + struct.pack("<I", _masked_crc(payload)))


class EventFileWriter:
    """Appends scalar events to one `events.out.tfevents.*` file.

    Every flush appends only the not-yet-written delta through
    `storage.append_bytes` — linear total bytes over a run for local
    AND gs:// paths (GCS appends ride the two-source compose there).
    """

    def __init__(self, log_dir):
        self.log_dir = str(log_dir)
        if not storage.is_gcs_path(self.log_dir):
            os.makedirs(self.log_dir, exist_ok=True)
        # ts.host.pid.counter: same uniqueness recipe as TF's own
        # writers — two writers in the same second (fast tests,
        # back-to-back fits into one dir) must not interleave streams.
        global _WRITER_COUNT
        _WRITER_COUNT += 1
        name = "events.out.tfevents.{:.0f}.{}.{}.{}".format(
            time.time(), socket.gethostname(), os.getpid(),
            _WRITER_COUNT)
        self.path = storage.join(self.log_dir, name)
        self._buffer = bytearray(_frame(encode_file_version()))
        self.flush()

    def add_scalars(self, step, scalars, wall_time=None):
        self._buffer.extend(_frame(
            encode_scalar_event(step, scalars, wall_time=wall_time)))

    def flush(self):
        # Pending frames only: the buffer is cleared once appended, so
        # writer memory stays bounded however long the run.
        if self._buffer:
            storage.append_bytes(self.path, bytes(self._buffer))
            self._buffer = bytearray()

    def close(self):
        self.flush()


# -- Structured job events (JSONL side channel) -------------------------


def _process_index():
    """This process's index in a multi-process job: the
    CLOUD_TPU_PROCESS_ID env contract first, a jax that is ALREADY
    imported second (`sys.modules.get` — logging an event must never
    pull jax in), else 0."""
    value = os.environ.get("CLOUD_TPU_PROCESS_ID")
    if value is not None:
        try:
            return int(value)
        except ValueError:
            return 0
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:
            return 0
    return 0


def log_job_event(kind, payload, path=None):
    """Appends one structured job event as a JSONL line.

    The scalar event files above are the TensorBoard-compat channel;
    this is the machine-readable side channel for launch-time facts
    that have no step axis — preflight lint findings, deploy
    decisions, preemption notices. `path` defaults to the
    CLOUD_TPU_EVENT_LOG environment variable; when neither is set the
    call is a no-op (returns None), so library code can log
    unconditionally. Local and gs:// paths both work (appends ride
    `storage.append_bytes`).

    Every record carries the writer's identity and both clocks: host +
    pid + process_index so the fleet collector can tell two workers'
    events apart (they used to be indistinguishable), wall time for
    humans, and a monotonic stamp for intra-process ordering/ages that
    survives NTP steps.

    Returns the path written to, or None when logging is disabled.
    """
    path = path or os.environ.get("CLOUD_TPU_EVENT_LOG")
    if not path:
        return None
    record = {"time": time.time(), "monotonic": time.monotonic(),
              "host": socket.gethostname(), "pid": os.getpid(),
              "process_index": _process_index(),
              "kind": kind, "payload": payload}
    storage.append_bytes(
        path, (json.dumps(record, sort_keys=True) + "\n").encode("utf-8"))
    return path


def read_job_events(path, with_stats=False, kind=None):
    """Parses a JSONL job-event file -> list of dicts.

    Skips blanks AND corrupt/partial lines (a writer that crashed
    mid-append, or two unsynchronized appenders interleaving) with one
    warning for the whole file — a single torn line must not poison
    every later reader of an otherwise-healthy log. With
    `with_stats=True` returns (records, {"corrupt_lines": n}) so the
    fleet collector can report torn files instead of silently eating
    them. `kind` filters to one event kind (e.g. "graftguard",
    "graftchaos", "graftwatch") — the common post-hoc assertion shape
    in the chaos-smoke CI job and tests.
    """
    data = storage.read_bytes(path).decode("utf-8", errors="replace")
    records = []
    corrupt = 0
    for line in data.splitlines():
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            corrupt += 1
    if corrupt:
        logger.warning(
            "read_job_events: skipped %d corrupt/partial JSON line(s) "
            "in %s (crashed writer?); returning the %d parseable "
            "record(s).", corrupt, path, len(records))
    if kind is not None:
        records = [r for r in records if r.get("kind") == kind]
    if with_stats:
        return records, {"corrupt_lines": corrupt}
    return records


# -- Reader (tests + tuner-side readback) -------------------------------


def _read_varint(data, pos):
    shift, value = 0, 0
    while True:
        byte = data[pos]
        pos += 1
        value |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return value, pos
        shift += 7


def _parse_fields(data):
    """Yields (field_number, wire_type, value) over one message."""
    pos = 0
    while pos < len(data):
        key, pos = _read_varint(data, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            value, pos = _read_varint(data, pos)
        elif wire == 1:
            value = data[pos:pos + 8]
            pos += 8
        elif wire == 2:
            length, pos = _read_varint(data, pos)
            value = data[pos:pos + length]
            pos += length
        elif wire == 5:
            value = data[pos:pos + 4]
            pos += 4
        else:
            raise ValueError("Unsupported wire type {}.".format(wire))
        yield field, wire, value


def read_events(path):
    """Parses an event file -> [(step, {tag: value})], scalars only.

    Verifies the TFRecord CRCs — a truncated or corrupted file fails
    loudly instead of yielding garbage floats.
    """
    data = storage.read_bytes(path)
    events = []
    pos = 0
    while pos < len(data):
        if pos + 12 > len(data):
            raise ValueError(
                "Truncated event file (partial record header): "
                "{}".format(path))
        header = data[pos:pos + 8]
        (length,) = struct.unpack("<Q", header)
        if pos + 16 + length > len(data):
            raise ValueError(
                "Truncated event file (partial record payload): "
                "{}".format(path))
        (header_crc,) = struct.unpack("<I", data[pos + 8:pos + 12])
        if _masked_crc(header) != header_crc:
            raise ValueError("Corrupt event file (header crc): "
                             "{}".format(path))
        payload = data[pos + 12:pos + 12 + length]
        (payload_crc,) = struct.unpack(
            "<I", data[pos + 12 + length:pos + 16 + length])
        if _masked_crc(payload) != payload_crc:
            raise ValueError("Corrupt event file (payload crc): "
                             "{}".format(path))
        pos += 16 + length

        step, scalars = 0, {}
        for field, wire, value in _parse_fields(payload):
            if field == 2 and wire == 0:
                step = value
            elif field == 5 and wire == 2:
                for f2, w2, v2 in _parse_fields(value):
                    if f2 == 1 and w2 == 2:
                        tag, number = None, None
                        for f3, w3, v3 in _parse_fields(v2):
                            if f3 == 1 and w3 == 2:
                                tag = v3.decode("utf-8")
                            elif f3 == 2 and w3 == 5:
                                (number,) = struct.unpack("<f", v3)
                        if tag is not None and number is not None:
                            scalars[tag] = number
        if scalars:
            events.append((step, scalars))
    return events


__all__ = ["EventFileWriter", "read_events", "crc32c",
           "encode_scalar_event", "log_job_event", "read_job_events"]
