"""Google API client utilities: telemetry header + job-status polling.

Reference parity: utils/google_api_client.py:27-78.
"""

import logging
import time

from cloud_tpu import version

try:
    from googleapiclient import discovery
    from googleapiclient.http import HttpRequest
except ImportError:
    discovery = None
    HttpRequest = object

logger = logging.getLogger("cloud_tpu")

_USER_AGENT = "cloud-tpu/{}".format(version.__version__)

# Terminal CAIP job states (reference google_api_client.py:56-66).
_SUCCEEDED = "SUCCEEDED"
_FAILED = "FAILED"
_CANCELLED = "CANCELLED"


class CloudTpuHttpRequest(HttpRequest):
    """HttpRequest that tags every API call with the framework user-agent.

    Reference parity: `TFCloudHttpRequest`
    (utils/google_api_client.py:27-42) — the usage-telemetry channel.
    """

    def __init__(self, *args, **kwargs):
        headers = kwargs.setdefault("headers", {})
        headers["user-agent"] = _USER_AGENT
        super().__init__(*args, **kwargs)


def get_api_training_job_state(job_id, project_id, api_client=None):
    """Returns the current state string of a platform training job."""
    if api_client is None:
        if discovery is None:
            raise RuntimeError(
                "google-api-python-client is required to query job status.")
        api_client = discovery.build(
            "ml", "v1", cache_discovery=False,
            requestBuilder=CloudTpuHttpRequest)
    name = "projects/{}/jobs/{}".format(project_id, job_id)
    request = api_client.projects().jobs().get(name=name)
    response = request.execute()
    return response.get("state")


def wait_for_api_training_job_success(job_id, project_id, api_client=None,
                                      poll_interval_secs=30):
    """Blocks until the training job reaches a terminal state.

    Reference parity: utils/google_api_client.py:45-78 (30s poll loop
    until SUCCEEDED/FAILED).

    Returns:
        True on SUCCEEDED, False on FAILED/CANCELLED.
    """
    while True:
        state = get_api_training_job_state(job_id, project_id, api_client)
        if state == _SUCCEEDED:
            logger.info("Job %s succeeded.", job_id)
            return True
        if state in (_FAILED, _CANCELLED):
            logger.error("Job %s finished with state %s.", job_id, state)
            return False
        logger.info("Job %s state: %s; polling again in %ss.",
                    job_id, state, poll_interval_secs)
        time.sleep(poll_interval_secs)
