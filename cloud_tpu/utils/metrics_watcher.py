"""Incremental metrics-stream watcher.

TPU-native counterpart of the reference's TensorBoard event-file watcher
(`get_tensorboard_log_watcher_from_path`, reference utils/tf_utils.py:
27-51), which DistributingCloudTuner uses as its metrics return channel
(reference tuner/tuner.py:532-560, parsing `epoch_*` tag conventions out
of event streams). The native channel is structured jsonl written by
`cloud_tpu.training.callbacks.MetricsLogger` — one JSON object per
epoch — so the watcher is a byte-offset tail, not an event-proto parser,
and the fragile tag-prefix convention disappears (SURVEY §7.4.6).

Works over local paths and `gs://` objects through the storage seam;
remote objects are re-read and diffed by offset, mirroring how the
reference's DirectoryWatcher re-polls GCS.
"""

import json
import logging

from cloud_tpu.utils import storage

logger = logging.getLogger("cloud_tpu")


class MetricsWatcher:
    """Tails a metrics jsonl stream, yielding only records not yet seen.

    Usage (the tuner's live-readback loop):

        watcher = MetricsWatcher(path)
        while job_running():
            for record in watcher.poll():
                report(record)
    """

    def __init__(self, path):
        self.path = path
        self._offset = 0
        self._partial = b""
        self._warned_truncated = False

    def poll(self):
        """Returns the list of complete records appended since last poll.

        Missing files mean "not started yet" and return []. A trailing
        partial line (a concurrent writer mid-append) is buffered until
        its newline arrives. A stream SHORTER than the recorded offset
        means the object was truncated or rewritten (trial restart, log
        rotation): the watcher re-reads from 0 — with one warning per
        rotation — instead of silently yielding nothing forever.
        """
        if not storage.exists(self.path):
            return []
        data = storage.read_bytes(self.path)
        if len(data) < self._offset:
            if not self._warned_truncated:
                logger.warning(
                    "MetricsWatcher: %s shrank below the last read "
                    "offset (%d -> %d bytes); stream was truncated or "
                    "rewritten — re-reading from the start.",
                    self.path, self._offset, len(data))
                self._warned_truncated = True
            self._offset = 0
            self._partial = b""
        elif len(data) > self._offset:
            # Growth after a rotation re-arms the warning: each
            # rotation event warns once, not once per watcher lifetime.
            self._warned_truncated = False
        if len(data) <= self._offset:
            return []
        new = self._partial + data[self._offset:]
        self._offset = len(data)
        lines = new.split(b"\n")
        self._partial = lines.pop()
        records = []
        for line in lines:
            line = line.strip()
            if line:
                records.append(json.loads(line))
        return records


def get_metrics_watcher_from_path(path):
    """Factory mirroring the reference's watcher factory
    (reference utils/tf_utils.py:27-51)."""
    return MetricsWatcher(path)
