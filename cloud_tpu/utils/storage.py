"""Storage abstraction: local filesystem + GCS (`gs://`) paths.

The reference reads/writes GCS through TF's gfile and the
google-cloud-storage SDK scattered across modules (reference
cloud_fit/client.py:187-192, containerize.py:456-470). This module is the
single seam: local paths always work (tests, on-VM scratch), `gs://`
paths go through google-cloud-storage when installed.
"""

import os

try:
    from google.cloud import storage as gcs
except ImportError:
    gcs = None


def is_gcs_path(path):
    return str(path).startswith("gs://")


def _split_gcs(path):
    rest = str(path)[len("gs://"):]
    bucket, _, blob = rest.partition("/")
    return bucket, blob


def _client():
    if gcs is None:
        raise RuntimeError(
            "google-cloud-storage is required for gs:// paths.")
    return gcs.Client()


def write_bytes(path, data):
    if is_gcs_path(path):
        bucket_name, blob_name = _split_gcs(path)
        _client().bucket(bucket_name).blob(blob_name).upload_from_string(
            data)
        return
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


def read_bytes(path):
    if is_gcs_path(path):
        bucket_name, blob_name = _split_gcs(path)
        return (_client().bucket(bucket_name).blob(blob_name)
                .download_as_bytes())
    with open(path, "rb") as f:
        return f.read()


def exists(path):
    if is_gcs_path(path):
        bucket_name, blob_name = _split_gcs(path)
        return _client().bucket(bucket_name).blob(blob_name).exists()
    return os.path.exists(path)


def listdir(path):
    """Immediate child names under a directory (local or gs:// prefix).

    Missing directories list as empty (callers treat "nothing there yet"
    uniformly — e.g. checkpoint discovery on first run).
    """
    if is_gcs_path(path):
        bucket_name, prefix = _split_gcs(path)
        prefix = prefix.rstrip("/")
        prefix = prefix + "/" if prefix else ""  # "" = bucket root
        names = set()
        for blob in _client().bucket(bucket_name).list_blobs(
                prefix=prefix):
            rest = blob.name[len(prefix):]
            if rest:
                names.add(rest.split("/", 1)[0])
        return sorted(names)
    if not os.path.isdir(path):
        return []
    return sorted(os.listdir(path))


def join(base, *parts):
    if is_gcs_path(base):
        return "/".join([str(base).rstrip("/")] +
                        [str(p).strip("/") for p in parts])
    return os.path.join(base, *parts)
