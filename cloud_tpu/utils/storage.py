"""Storage abstraction: local filesystem + GCS (`gs://`) paths.

The reference reads/writes GCS through TF's gfile and the
google-cloud-storage SDK scattered across modules (reference
cloud_fit/client.py:187-192, containerize.py:456-470). This module is the
single seam: local paths always work (tests, on-VM scratch), `gs://`
paths go through google-cloud-storage when installed.
"""

import os

try:
    from google.cloud import storage as gcs
except ImportError:
    gcs = None


def is_gcs_path(path):
    return str(path).startswith("gs://")


def _split_gcs(path):
    rest = str(path)[len("gs://"):]
    bucket, _, blob = rest.partition("/")
    return bucket, blob


def _client():
    if gcs is None:
        raise RuntimeError(
            "google-cloud-storage is required for gs:// paths.")
    return gcs.Client()


def write_bytes(path, data):
    if is_gcs_path(path):
        bucket_name, blob_name = _split_gcs(path)
        _client().bucket(bucket_name).blob(blob_name).upload_from_string(
            data)
        return
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


def append_bytes(path, data):
    """Appends to a file or gs:// object with linear total bytes.

    GCS has no append primitive; the object is extended server-side via
    a two-source compose (existing + new part), so per-call cost is the
    new part, not the accumulated stream — O(total) bytes over a run
    instead of rewriting the whole stream every call.
    """
    if is_gcs_path(path):
        import uuid

        bucket_name, blob_name = _split_gcs(path)
        bucket = _client().bucket(bucket_name)
        dest = bucket.blob(blob_name)
        if not dest.exists():
            dest.upload_from_string(data)
            return
        # Unique part name: concurrent appenders never clobber each
        # other's staged bytes, and a crash leaves only an orphan part
        # (never silently reused). The compose is guarded by a
        # generation precondition so two concurrent composes can't
        # drop each other's records; on contention, reload and retry.
        part = bucket.blob("{}.part.{}".format(blob_name, uuid.uuid4().hex))
        part.upload_from_string(data)
        try:
            try:
                from google.api_core import exceptions as api_exceptions
                precondition_failed = api_exceptions.PreconditionFailed
            except ImportError:  # pragma: no cover - ships with the SDK
                precondition_failed = ()
            for _ in range(5):
                dest.reload()
                try:
                    dest.compose([dest, part],
                                 if_generation_match=dest.generation)
                    return
                except precondition_failed:
                    continue  # another appender won; re-read and retry
            raise RuntimeError(
                "append_bytes: persistent compose contention on "
                "{}".format(path))
        finally:
            part.delete()
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    with open(path, "ab") as f:
        f.write(data)


def read_bytes(path):
    if is_gcs_path(path):
        bucket_name, blob_name = _split_gcs(path)
        return (_client().bucket(bucket_name).blob(blob_name)
                .download_as_bytes())
    with open(path, "rb") as f:
        return f.read()


def exists(path):
    if is_gcs_path(path):
        bucket_name, blob_name = _split_gcs(path)
        return _client().bucket(bucket_name).blob(blob_name).exists()
    return os.path.exists(path)


def listdir(path):
    """Immediate child names under a directory (local or gs:// prefix).

    Missing directories list as empty (callers treat "nothing there yet"
    uniformly — e.g. checkpoint discovery on first run).
    """
    if is_gcs_path(path):
        bucket_name, prefix = _split_gcs(path)
        prefix = prefix.rstrip("/")
        prefix = prefix + "/" if prefix else ""  # "" = bucket root
        # delimiter="/" makes GCS aggregate children server-side: one
        # page of names instead of enumerating every blob under the
        # prefix (an orbax checkpoint tree holds thousands of shards).
        names = set()
        listing = _client().bucket(bucket_name).list_blobs(
            prefix=prefix, delimiter="/")
        for blob in listing:
            rest = blob.name[len(prefix):]
            if rest:
                names.add(rest)
        names.update(p[len(prefix):].rstrip("/") for p in listing.prefixes)
        return sorted(names)
    if not os.path.isdir(path):
        return []
    return sorted(os.listdir(path))


def join(base, *parts):
    if is_gcs_path(base):
        return "/".join([str(base).rstrip("/")] +
                        [str(p).strip("/") for p in parts])
    return os.path.join(base, *parts)
